"""Mesh-sharded query lane (ISSUE 6): equivalence vs the fan-out,
single-fetch/zero-host-merge counters, the mesh-stack cache lifecycle,
the fallback ladder, and the distributed-search satellites.

The mesh lane replaces the coordinator's thread-pool fan-out (S device
fetches + a host-side cross-shard merge per multi-shard query) with ONE
shard_map program over the ("replica", "shard") mesh: per-shard stacked
execution, in-shard merge AND the cross-shard top-k reduce fused on
device. These tests pin the contract:

  * mesh results are bitwise-identical to the concurrent fan-out across
    the mesh-native query-shape matrix (same stable merge order, same
    score dtype promotion);
  * a multi-shard mesh query performs exactly ONE device_fetch and ZERO
    host-side per-shard merges (counter-asserted);
  * the mesh stack is fielddata-breaker-charged and invalidated by
    refresh/merge/`_cache/clear`/close;
  * the fallback ladder — sorted bodies, unsupported plans, opt-out
    settings, more shards than devices, oversized/declined stacks,
    cross-host clusters — lands on the fan-out, never errors.
"""

import json

import numpy as np
import pytest

from elasticsearch_tpu.node import NodeService

N_SHARDS = 4
WORDS = ["quick", "brown", "fox", "jumps", "lazy", "dog", "sleeps",
         "swift", "river", "stone"]

# mesh-native query shapes (every node type with a typed mesh handler)
MESH_QUERIES = [
    {"match_all": {}},
    {"bool": {"should": [{"match": {"body": "fox"}},
                         {"match": {"body": "dog"}}]}},
    {"bool": {"should": [{"match": {"body": "quick"}}],
              "filter": [{"range": {"n": {"gte": 2, "lt": 60}}}]}},
    {"term": {"tag": "t1"}},
    {"terms": {"tag": ["t0", "t2"]}},
    {"term": {"n": 4}},
    {"term": {"price": 6.5}},
    {"range": {"n": {"gt": 30}}},
    {"range": {"price": {"gte": 2.0, "lt": 50.0}}},
    {"range": {"tag": {"gte": "t0", "lte": "t1"}}},
    {"exists": {"field": "price"}},
    {"exists": {"field": "body"}},
    {"ids": {"values": ["1", "5", "8", "77"]}},
    {"ids": {"values": ["zzz-absent"]}},
    {"constant_score": {"filter": {"term": {"tag": "t1"}}, "boost": 2.5}},
    {"dis_max": {"queries": [{"match": {"body": "fox"}},
                             {"match": {"body": "dog"}}],
                 "tie_breaker": 0.4}},
    {"bool": {"must": [{"match": {"body": "fox"}}],
              "must_not": [{"term": {"tag": "t2"}}],
              "should": [{"match": {"body": "brown"}}]}},
    {"bool": {"should": [{"match": {"body": {"query": "fox brown",
                                             "operator": "and"}}}]}},
    {"bool": {"should": [{"match": {"body": "quick"}},
                         {"match": {"body": "river"}}],
              "minimum_should_match": 2}},
]

DENSE_Q = {"size": 5, "query": {"bool": {
    "should": [{"match": {"body": "quick"}}, {"match": {"body": "fox"}}]}}}

MAPPING = {"_doc": {"properties": {
    "body": {"type": "string"},
    "tag": {"type": "string", "index": "not_analyzed"},
    "n": {"type": "long"},
    "price": {"type": "double"}}}}


def _fill(n, names, shards=N_SHARDS, rounds=3, per_round=16):
    for name in names:
        if name not in n.indices:
            n.create_index(name, settings={"number_of_shards": shards},
                           mappings=MAPPING)
    di = 0
    for _ in range(rounds):
        for _ in range(per_round):
            doc = {"body": f"{WORDS[di % 10]} {WORDS[(di * 3 + 1) % 10]} "
                           f"{WORDS[(di * 7 + 2) % 10]}",
                   "tag": f"t{di % 3}", "n": di}
            if di % 2 == 0:
                doc["price"] = di / 2.0
            for name in names:
                n.index_doc(name, str(di), dict(doc))
            di += 1
        for name in names:
            n.refresh(name)
    return di


@pytest.fixture(scope="module")
def pair(tmp_path_factory):
    """Two identical 4-shard corpora: "m" on the mesh lane, "f" pinned to
    the concurrent fan-out (`index.search.mesh.enable: false`). Same doc
    ids -> same routing -> identical shard layouts."""
    n = NodeService(str(tmp_path_factory.mktemp("mesh")))
    n.create_index("m", settings={"number_of_shards": N_SHARDS},
                   mappings=MAPPING)
    n.create_index("f", settings={"number_of_shards": N_SHARDS,
                                  "index.search.mesh.enable": False},
                   mappings=MAPPING)
    _fill(n, ["m", "f"])
    yield n
    n.close()


def _hits(resp):
    return [(h["_id"], h["_score"]) for h in resp["hits"]["hits"]]


def _search(n, name, q, **extra):
    return n.search(name, json.loads(json.dumps(
        {"size": 10, "query": q, **extra})))


class TestMeshEquivalence:
    @pytest.mark.parametrize("q", MESH_QUERIES,
                             ids=[json.dumps(q)[:48] for q in MESH_QUERIES])
    def test_bitwise_identical_to_fanout(self, pair, q):
        n = pair
        before = n.indices["m"].search_stats.get("mesh", 0)
        got = _search(n, "m", q)
        assert n.indices["m"].search_stats.get("mesh", 0) == before + 1, \
            f"mesh lane did not engage for {q}"
        want = _search(n, "f", q)
        assert n.indices["f"].search_stats.get("mesh", 0) == 0
        assert got["hits"]["total"] == want["hits"]["total"], q
        assert got["hits"]["max_score"] == want["hits"]["max_score"], q
        assert _hits(got) == _hits(want), q

    def test_deep_pagination_identical(self, pair):
        n = pair
        q = {"match_all": {}}
        got = _search(n, "m", q, size=40, **{"from": 5})
        want = _search(n, "f", q, size=40, **{"from": 5})
        assert _hits(got) == _hits(want)
        assert got["hits"]["total"] == want["hits"]["total"]
        assert len(got["hits"]["hits"]) == 40

    def test_tombstones_identical(self, pair):
        n = pair
        for name in ("m", "f"):
            n.delete_doc(name, "7")
            n.refresh(name)
        q = {"bool": {"should": [{"match": {"body": "fox"}},
                                 {"match": {"body": "dog"}}]}}
        got = _search(n, "m", q, size=96)
        want = _search(n, "f", q, size=96)
        assert _hits(got) == _hits(want)
        assert "7" not in [h for h, _s in _hits(got)]

    def test_shards_section_all_successful(self, pair):
        out = _search(pair, "m", {"match_all": {}})
        assert out["_shards"] == {"total": N_SHARDS,
                                  "successful": N_SHARDS, "failed": 0}


class TestMeshCounters:
    def test_one_fetch_zero_host_merges(self, pair):
        from elasticsearch_tpu.common.metrics import (host_merge_count,
                                                      transfer_snapshot)
        n = pair
        n.search("m", json.loads(json.dumps(DENSE_Q)))        # warm
        f0 = transfer_snapshot()["device_fetches_total"]
        h0 = host_merge_count()
        n.search("m", json.loads(json.dumps(DENSE_Q)))
        assert transfer_snapshot()["device_fetches_total"] - f0 == 1, \
            "a multi-shard mesh query must pay exactly ONE device fetch"
        assert host_merge_count() - h0 == 0, \
            "the mesh lane must not run the host-side cross-shard merge"

    def test_fanout_pays_per_shard(self, pair):
        from elasticsearch_tpu.common.metrics import (host_merge_count,
                                                      transfer_snapshot)
        n = pair
        n.search("f", json.loads(json.dumps(DENSE_Q)))        # warm
        f0 = transfer_snapshot()["device_fetches_total"]
        h0 = host_merge_count()
        n.search("f", json.loads(json.dumps(DENSE_Q)))
        assert transfer_snapshot()["device_fetches_total"] - f0 == N_SHARDS
        assert host_merge_count() - h0 == 1

    def test_profile_query_paths_mesh(self, pair):
        out = pair.search("m", {"profile": True,
                                **json.loads(json.dumps(DENSE_Q))})
        assert out["profile"]["device"]["query_paths"].get("mesh", 0) == 1

    def test_trace_mesh_reduce_span(self, pair):
        n = pair
        with n.tracer.request("mesh-span-test", force=True):
            n.search("m", json.loads(json.dumps(DENSE_Q)))
        trace = n.tracer.list()[0]
        full = n.tracer.get(trace["trace_id"])
        assert any(s["name"] == "mesh_reduce" for s in full["spans"])
        # zero shard fan-out subtrees: the collective replaced them
        assert not any(s["name"] == "shard" for s in full["spans"])


class TestFallbackLadder:
    def test_sorted_rides_the_mesh_but_score_sort_declines(self, pair):
        """ISSUE 17: encoded-key sorts no longer decline the mesh — the
        cross-shard merge ranks by key on device. Sorts the encoding
        can't bitwise-reproduce (a `_score` key) still fall back."""
        n = pair
        before = n.indices["m"].search_stats.get("mesh", 0)
        body = {"size": 10, "query": {"match_all": {}},
                "sort": [{"n": {"order": "desc"}}]}
        out = n.search("m", json.loads(json.dumps(body)))
        ids = [h["_id"] for h in out["hits"]["hits"]]
        assert ids == sorted(ids, key=int, reverse=True)[:len(ids)]
        assert n.indices["m"].search_stats.get("mesh", 0) == before + 1
        before = n.indices["m"].search_stats.get("mesh", 0)
        declined = {"size": 10, "query": {"match": {"body": "quick"}},
                    "sort": [{"n": "asc"}, "_score"]}
        n.search("m", json.loads(json.dumps(declined)))
        assert n.indices["m"].search_stats.get("mesh", 0) == before

    def test_unsupported_plan_falls_back(self, pair):
        n = pair
        before = n.indices["m"].search_stats.get("mesh", 0)
        out = _search(n, "m", {"prefix": {"body": "qu"}})
        assert out["hits"]["total"] > 0
        assert n.indices["m"].search_stats.get("mesh", 0) == before

    def test_supported_aggs_ride_the_mesh(self, pair):
        """ISSUE 11: terms/histogram/metric aggs no longer decline — the
        partials collect INSIDE the mesh program and merge identically to
        the fan-out's per-shard collect."""
        n = pair
        body = {"size": 5, "query": {"match_all": {}},
                "aggs": {"tags": {"terms": {"field": "tag"}},
                         "ns": {"histogram": {"field": "n",
                                              "interval": 10}},
                         "ps": {"stats": {"field": "price"}}}}
        before = n.indices["m"].search_stats.get("mesh_agg_dispatches", 0)
        got = n.search("m", json.loads(json.dumps(body)),
                       request_cache=False)
        assert n.indices["m"].search_stats.get("mesh_agg_dispatches", 0) \
            == before + 1
        want = n.search("f", json.loads(json.dumps(body)),
                        request_cache=False)
        assert got["aggregations"] == want["aggregations"]
        assert _hits(got) == _hits(want)
        assert got["hits"]["total"] == want["hits"]["total"]

    def test_unsupported_aggs_fall_back(self, pair):
        """Specs without a mesh form (HLL cardinality, sub-aggs) keep the
        fan-out — counted as mesh_agg_fallbacks."""
        n = pair
        before = n.indices["m"].search_stats.get("mesh", 0)
        fb = n.indices["m"].search_stats.get("mesh_agg_fallbacks", 0)
        body = {"size": 0, "query": {"match_all": {}},
                "aggs": {"card": {"cardinality": {"field": "tag"}}}}
        out = n.search("m", json.loads(json.dumps(body)),
                       request_cache=False)
        assert out["aggregations"]["card"]["value"] == 3
        assert n.indices["m"].search_stats.get("mesh", 0) == before
        assert n.indices["m"].search_stats.get("mesh_agg_fallbacks", 0) \
            == fb + 1

    def test_more_shards_than_devices_falls_back(self, tmp_path):
        import jax
        n = NodeService(str(tmp_path / "wide"))
        try:
            shards = len(jax.devices()) * 2     # S_pad > device count
            n.create_index("w", settings={"number_of_shards": shards},
                           mappings=MAPPING)
            for i in range(32):
                n.index_doc("w", str(i), {"body": f"quick fox {i}", "n": i})
            n.refresh("w")
            out = n.search("w", json.loads(json.dumps(DENSE_Q)))
            assert out["hits"]["total"] > 0
            assert n.indices["w"].search_stats.get("mesh", 0) == 0
        finally:
            n.close()

    def test_oversized_stack_declined(self, tmp_path):
        from elasticsearch_tpu.common.settings import Settings
        n = NodeService(str(tmp_path / "tiny"),
                        settings=Settings({"indices.mesh.cache.size": 64}))
        try:
            _fill(n, ["t"], rounds=2, per_round=8)
            out = n.search("t", json.loads(json.dumps(DENSE_Q)))
            assert out["hits"]["total"] > 0
            assert n.indices["t"].search_stats.get("mesh", 0) == 0
            assert n.caches.mesh_stacks.stats()["oversized"] >= 1
        finally:
            n.close()

    def test_node_level_opt_out(self, tmp_path):
        from elasticsearch_tpu.common.settings import Settings
        n = NodeService(str(tmp_path / "off"), settings=Settings(
            {"node.search.mesh.enable": False}))
        try:
            _fill(n, ["t"], rounds=2, per_round=8)
            out = n.search("t", json.loads(json.dumps(DENSE_Q)))
            assert out["hits"]["total"] > 0
            assert n.indices["t"].search_stats.get("mesh", 0) == 0
        finally:
            n.close()

    def test_cross_host_cluster_falls_back(self, tmp_path):
        """Shards spread over cluster nodes never see the mesh lane: the
        cluster driver fans out over the transport and merges host-side
        (the inter-host RPC half of SURVEY §5.8's topology)."""
        from elasticsearch_tpu.cluster import TestCluster
        from elasticsearch_tpu.parallel import mesh_exec
        cluster = TestCluster(2, str(tmp_path / "cluster"))
        try:
            client = cluster.client()
            client.create_index("docs", {"number_of_shards": 2,
                                         "number_of_replicas": 0})
            cluster.ensure_green()
            for i in range(20):
                client.index_doc("docs", str(i),
                                 {"body": f"quick brown fox {i}"})
            client.refresh("docs")
            st0 = mesh_exec.program_cache_stats()
            lookups0 = st0["hits_total"] + st0["misses_total"]
            out = client.search("docs", json.loads(json.dumps(DENSE_Q)))
            assert out["hits"]["total"] == 20
            st1 = mesh_exec.program_cache_stats()
            assert st1["hits_total"] + st1["misses_total"] == lookups0, \
                "no mesh program may run for cluster-spread shards"
        finally:
            cluster.close()


@pytest.fixture()
def node(tmp_path):
    n = NodeService(str(tmp_path / "node"))
    yield n
    n.close()


class TestMeshStackCache:
    def test_breaker_charged_and_released(self, node):
        _fill(node, ["t"])
        br = node.breakers.breaker("fielddata")
        used0 = br.used
        node.search("t", json.loads(json.dumps(DENSE_Q)))
        st = node.caches.mesh_stacks.stats()
        assert st["entries"] == 1
        assert st["memory_size_in_bytes"] > 0
        assert br.used >= used0 + st["memory_size_in_bytes"]
        cleared = node.caches.clear(query=True)
        assert cleared["mesh_stack"] == 1
        assert node.caches.mesh_stacks.stats()["entries"] == 0
        assert br.used <= used0 + 1

    def test_refresh_invalidates(self, node):
        _fill(node, ["t"])
        node.search("t", json.loads(json.dumps(DENSE_Q)))
        assert node.caches.mesh_stacks.stats()["entries"] == 1
        node.index_doc("t", "zzz", {"body": "new doc", "n": 999})
        node.refresh("t")
        assert node.caches.mesh_stacks.stats()["entries"] == 0
        node.search("t", json.loads(json.dumps(DENSE_Q)))
        assert node.caches.mesh_stacks.stats()["entries"] == 1

    def test_merge_invalidates(self, node):
        _fill(node, ["t"])
        node.search("t", json.loads(json.dumps(DENSE_Q)))
        node.force_merge("t")
        assert node.caches.mesh_stacks.stats()["entries"] == 0
        out = node.search("t", json.loads(json.dumps(DENSE_Q)))
        assert out["hits"]["total"] > 0

    def test_cache_clear_http(self, node):
        import http.client

        from elasticsearch_tpu.rest import HttpServer
        _fill(node, ["t"])
        node.search("t", json.loads(json.dumps(DENSE_Q)))
        assert node.caches.mesh_stacks.stats()["entries"] == 1
        server = HttpServer(node, port=0).start()
        try:
            conn = http.client.HTTPConnection("127.0.0.1", server.port)
            conn.request("POST", "/t/_cache/clear?query=true")
            resp = conn.getresponse()
            out = json.loads(resp.read())
            assert resp.status == 200
            assert out["cleared"]["mesh_stack"] == 1
        finally:
            server.stop()
        assert node.caches.mesh_stacks.stats()["entries"] == 0

    def test_index_close_clears(self, node):
        _fill(node, ["t"])
        node.search("t", json.loads(json.dumps(DENSE_Q)))
        assert node.caches.mesh_stacks.stats()["entries"] == 1
        node.close_index("t")
        assert node.caches.mesh_stacks.stats()["entries"] == 0

    def test_delete_serves_via_liveness_not_rebuild(self, node):
        _fill(node, ["t"])
        out1 = node.search("t", json.loads(json.dumps(DENSE_Q)))
        total1 = out1["hits"]["total"]
        victim = out1["hits"]["hits"][0]["_id"]
        node.delete_doc("t", victim)
        node.indices["t"].refresh()
        out2 = node.search("t", json.loads(json.dumps(DENSE_Q)))
        assert out2["hits"]["total"] == total1 - 1
        assert victim not in [h["_id"] for h in out2["hits"]["hits"]]


class TestMeshMetrics:
    def test_scrape_families_and_sampler(self, node):
        _fill(node, ["t"])
        node.search("t", json.loads(json.dumps(DENSE_Q)))
        from elasticsearch_tpu.common.metrics import render_openmetrics
        text = render_openmetrics(node.metric_sections())
        assert "es_search_mesh_dispatches_total" in text
        assert "es_search_host_merges_total" in text
        assert 'cache="mesh_stack"' in text
        snap = node._sampler_snapshot()
        assert snap["mesh_stack_cache_memory_bytes"] > 0
        assert node.stats()["caches"]["mesh_stack"]["entries"] == 1


# -- distributed-search satellites (ISSUE 6) --------------------------------

class TestDistributedSatellites:
    def test_knn_replica_padding_rows_masked(self):
        """Q not divisible by n_replicas pads with all-zero query vectors;
        pad rows must contribute -inf inside the step (never NaN through
        cosine 0/0) and the [:Q] rows must come back NaN-free."""
        import jax

        from elasticsearch_tpu.index.segment import SegmentBuilder
        from elasticsearch_tpu.mapping.mapper import MapperService
        from elasticsearch_tpu.parallel import (DistributedSearcher,
                                                PackedIndex, make_mesh,
                                                shard_id)
        rng = np.random.default_rng(7)
        ms = MapperService(mappings={"_doc": {"properties": {
            "v": {"type": "dense_vector", "dims": 8}}}})
        mapper = ms.document_mapper("_doc")
        builders = [SegmentBuilder(seg_id=i) for i in range(4)]
        for i in range(24):
            vec = rng.normal(0, 1, 8).astype(np.float32)
            builders[shard_id(str(i), 4)].add(
                mapper.parse({"v": [float(x) for x in vec]},
                             doc_id=str(i)), "_doc")
        shards = [b.build() for b in builders]
        mesh = make_mesh(n_shards=4, n_replicas=2,
                         devices=jax.devices()[:8])
        ds = DistributedSearcher(index=PackedIndex.from_segments(shards),
                                 mesh=mesh).place()
        qv = rng.normal(0, 1, (3, 8)).astype(np.float32)   # pads to 4
        scores, keys = ds.search_knn("v", qv, k=5, metric="cosine")
        assert scores.shape == (3, 5)
        assert not np.isnan(scores).any()
        assert (keys >= 0).all()

    def test_step_memo_is_bounded_cache(self):
        """DistributedSearcher's compiled-step memo rides the common
        Cache core (bounded, observable) and still memoizes."""
        from elasticsearch_tpu.common.cache import Cache
        from elasticsearch_tpu.index.segment import SegmentBuilder
        from elasticsearch_tpu.mapping.mapper import MapperService
        from elasticsearch_tpu.parallel import (DistributedSearcher,
                                                PackedIndex, make_mesh)
        ms = MapperService()
        mapper = ms.document_mapper("_doc")
        b = SegmentBuilder(seg_id=0)
        b.add(mapper.parse({"body": "quick fox"}, doc_id="0"), "_doc")
        ds = DistributedSearcher(
            index=PackedIndex.from_segments([b.build()]),
            mesh=make_mesh(n_shards=1, n_replicas=1))
        assert isinstance(ds._step_cache, Cache)
        s1 = ds.build_step(Wt=8, k=5)
        assert ds.build_step(Wt=8, k=5) is s1
        assert ds._step_cache.stats()["entries"] == 1


class TestMeshKnn:
    """IVF kNN through the mesh program (ISSUE 11): one collective
    program + one fetch for a multi-shard kNN body, bitwise-identical to
    the per-shard fan-out; exact/mixed lanes keep the fan-out."""

    D = 8

    @pytest.fixture(scope="class")
    def knn_pair(self, tmp_path_factory):
        n = NodeService(str(tmp_path_factory.mktemp("meshknn")))
        mapping = {"_doc": {"properties": {
            "body": {"type": "string"},
            "tag": {"type": "string", "index": "not_analyzed"},
            "vec": {"type": "dense_vector", "dims": self.D}}}}
        base = {"number_of_shards": 4, "index.knn.ivf.nlist": 8,
                "index.knn.ivf.min_docs": 16, "index.knn.precision": "f32"}
        n.create_index("vm", settings=dict(base), mappings=mapping)
        n.create_index("vf", settings={**base,
                                       "index.search.mesh.enable": False},
                       mappings=mapping)
        rng = np.random.RandomState(11)
        for i in range(360):
            doc = {"body": f"w{i % 7}", "tag": f"t{i % 3}",
                   "vec": [float(x) for x in rng.randn(self.D)]}
            for name in ("vm", "vf"):
                n.index_doc(name, str(i), dict(doc))
        for name in ("vm", "vf"):
            n.refresh(name)
        n._qv = [float(x) for x in rng.randn(self.D)]
        yield n
        n.close()

    def _both(self, n, knn, size=10):
        body = {"size": size, "knn": knn}
        got = n.search("vm", json.loads(json.dumps(body)))
        want = n.search("vf", json.loads(json.dumps(body)))
        return _hits(got), _hits(want), got, want

    @pytest.mark.parametrize("metric", ["cosine", "dot", "l2"])
    def test_ivf_knn_bitwise_identical(self, knn_pair, metric):
        n = knn_pair
        before = n.indices["vm"].search_stats.get("mesh_ann_dispatches", 0)
        g, w, got, want = self._both(
            n, {"field": "vec", "query_vector": n._qv, "k": 10,
                "metric": metric})
        assert n.indices["vm"].search_stats.get(
            "mesh_ann_dispatches", 0) == before + 1
        assert g == w
        assert got["hits"]["total"] == want["hits"]["total"]
        assert got["hits"]["max_score"] == want["hits"]["max_score"]

    def test_filtered_knn_identical(self, knn_pair):
        n = knn_pair
        g, w, *_ = self._both(
            n, {"field": "vec", "query_vector": n._qv, "k": 10,
                "filter": {"term": {"tag": "t1"}}}, size=5)
        assert g == w

    def test_one_fetch_for_the_whole_index(self, knn_pair):
        from elasticsearch_tpu.common.metrics import transfer_snapshot
        n = knn_pair
        body = {"size": 10, "knn": {"field": "vec",
                                    "query_vector": n._qv, "k": 10}}
        n.search("vm", json.loads(json.dumps(body)))          # warm
        f0 = transfer_snapshot()["device_fetches_total"]
        n.search("vm", json.loads(json.dumps(body)))
        assert transfer_snapshot()["device_fetches_total"] - f0 == 1

    def test_exact_pinned_falls_back(self, knn_pair):
        n = knn_pair
        fb0 = n.indices["vm"].search_stats.get("mesh_ann_fallbacks", 0)
        g, w, *_ = self._both(
            n, {"field": "vec", "query_vector": n._qv, "k": 10,
                "exact": True})
        assert g == w
        assert n.indices["vm"].search_stats.get(
            "mesh_ann_fallbacks", 0) == fb0 + 1
