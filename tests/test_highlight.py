"""Plain highlighter: fragment extraction, tags, stemming-aware matching,
multi-field and field-match semantics (ref search/highlight/
PlainHighlighter.java + HighlightPhase.java).
"""

import pytest

from elasticsearch_tpu.node import NodeService

MAPPING = {"_doc": {"properties": {
    "title": {"type": "text"},
    "body": {"type": "text", "analyzer": "english"},
}}}


@pytest.fixture()
def node(tmp_path):
    n = NodeService(data_path=str(tmp_path))
    n.create_index("hl", mappings=MAPPING)
    n.index_doc("hl", "1", {
        "title": "The quick brown fox",
        "body": "Foxes are running quickly through the brown forest. "
                "The quick fox jumped over the lazy dog near the river."})
    n.index_doc("hl", "2", {"title": "Slow snails", "body": "nothing here"})
    n.refresh("hl")
    yield n
    n.close()


class TestHighlight:
    def test_basic_fragments_and_tags(self, node):
        out = node.search("hl", {
            "query": {"match": {"title": "quick fox"}},
            "highlight": {"fields": {"title": {}}}})
        h = out["hits"]["hits"][0]
        assert h["_id"] == "1"
        frags = h["highlight"]["title"]
        assert any("<em>quick</em>" in f for f in frags)
        assert any("<em>fox</em>" in f for f in frags)

    def test_custom_tags(self, node):
        out = node.search("hl", {
            "query": {"match": {"title": "fox"}},
            "highlight": {"pre_tags": ["<b>"], "post_tags": ["</b>"],
                          "fields": {"title": {}}}})
        frags = out["hits"]["hits"][0]["highlight"]["title"]
        assert any("<b>fox</b>" in f for f in frags)

    def test_stemmed_query_highlights_surface_forms(self, node):
        # english analyzer stems run/running -> run; the highlighter must
        # still mark the surface forms in the text
        out = node.search("hl", {
            "query": {"match": {"body": "running"}},
            "highlight": {"fields": {"body": {}}}})
        frags = out["hits"]["hits"][0]["highlight"]["body"]
        assert any("<em>running</em>" in f.lower() for f in frags)

    def test_no_match_no_highlight_key(self, node):
        out = node.search("hl", {
            "query": {"match_all": {}},
            "highlight": {"fields": {"title": {}}}})
        h2 = next(h for h in out["hits"]["hits"] if h["_id"] == "2")
        assert "highlight" not in h2  # match_all has no terms to mark

    def test_require_field_match(self, node):
        # query matches on title; body highlight suppressed when
        # require_field_match is true
        out = node.search("hl", {
            "query": {"match": {"title": "fox"}},
            "highlight": {"require_field_match": True,
                          "fields": {"body": {}}}})
        h = out["hits"]["hits"][0]
        assert "highlight" not in h
        out = node.search("hl", {
            "query": {"match": {"title": "fox"}},
            "highlight": {"fields": {"body": {}}}})
        assert "highlight" in out["hits"]["hits"][0]

    def test_fragment_size_and_count(self, node):
        out = node.search("hl", {
            "query": {"match": {"body": "fox"}},
            "highlight": {"fields": {"body": {
                "fragment_size": 30, "number_of_fragments": 2}}}})
        frags = out["hits"]["hits"][0]["highlight"]["body"]
        assert 1 <= len(frags) <= 2
        assert all(len(f) <= 30 + 2 * len("<em></em>") + 10 for f in frags)

    def test_whole_field_with_zero_fragments(self, node):
        out = node.search("hl", {
            "query": {"match": {"title": "fox"}},
            "highlight": {"fields": {"title": {"number_of_fragments": 0}}}})
        frags = out["hits"]["hits"][0]["highlight"]["title"]
        assert len(frags) == 1
        assert frags[0] == "The quick brown <em>fox</em>"


def test_fvh_highlighter_centers_fragments(tmp_path):
    """type: fvh — match-centered fragments scored by distinct terms
    (ref FastVectorHighlighter / postings highlighter passage scoring)."""
    from elasticsearch_tpu.node import NodeService
    node = NodeService(str(tmp_path / "fvh"))
    node.create_index("h")
    filler = "filler " * 40
    node.index_doc("h", "1", {"body": f"{filler}quick brown fox{filler}"
                                      f"only quick here{filler}"})
    node.refresh("h")
    out = node.search("h", {
        "query": {"match": {"body": "quick brown"}},
        "highlight": {"fields": {"body": {"type": "fvh",
                                          "fragment_size": 60,
                                          "number_of_fragments": 1}}}})
    frags = out["hits"]["hits"][0]["highlight"]["body"]
    # the single best fragment is the TWO-distinct-term cluster, centered
    assert len(frags) == 1
    assert "<em>quick</em>" in frags[0] and "<em>brown</em>" in frags[0]
    # plain type still works through the same request shape
    out2 = node.search("h", {
        "query": {"match": {"body": "quick"}},
        "highlight": {"fields": {"body": {"type": "plain"}}}})
    assert out2["hits"]["hits"][0]["highlight"]["body"]
    node.close()
