"""Test harness: force a virtual 8-device CPU mesh before JAX initializes.

Mirrors the reference's in-JVM multi-node test model (InternalTestCluster,
/root/reference/src/test/java/org/elasticsearch/test/InternalTestCluster.java:135):
many "nodes"/devices inside one process, no real cluster needed.
"""

import os

os.environ["JAX_PLATFORMS"] = "cpu"
_flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in _flags:
    os.environ["XLA_FLAGS"] = (_flags + " --xla_force_host_platform_device_count=8").strip()

import jax  # noqa: E402

# The hosted environment prepends its own TPU platform to jax_platforms even
# when the env var says cpu; re-pin after import (before backend init).
jax.config.update("jax_platforms", "cpu")

import pytest  # noqa: E402

# Arm the chaos leak detectors for the WHOLE suite: every Engine.close()
# under pytest asserts searcher refcounts drained, the per-site breaker
# ledger balanced, and no fielddata entries outliving the engine — a leak
# anywhere fails the leaking test by name instead of silently inflating
# the parent breaker for the tests behind it.
from elasticsearch_tpu.testing.chaos import detectors as _chaos_detectors  # noqa: E402

_chaos_detectors.arm()


def pytest_configure(config):
    config.addinivalue_line(
        "markers", "chaos: seeded randomized disruption rounds "
        "(CHAOS_SEED / CHAOS_ROUNDS env knobs)")
    config.addinivalue_line("markers", "slow: excluded from the tier-1 run")


@pytest.fixture(scope="session")
def devices():
    return jax.devices()
