"""bench.py always-emit guard (ISSUE 5 satellite — the r05 regression).

Round 5 exited rc=124 with NO one-line JSON ("parsed": null): the harness
timeout struck while a leg hung on an experimental platform and the
bailout handler wasn't armed yet. The guards now install at module import
— BEFORE the first leg — so a forced hang still prints the headline line:
SIGALRM at the budget edge, SIGTERM/SIGINT from the harness's first
strike. `BENCH_SELFTEST_HANG=1` simulates the hang without touching jax,
keeping this tier-1 fast.
"""

import json
import os
import signal
import subprocess
import sys
import time

BENCH = os.path.join(os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))), "bench.py")


def _env(**extra):
    env = dict(os.environ)
    env.update({"BENCH_SELFTEST_HANG": "1", "JAX_PLATFORMS": "cpu"})
    env.update({k: str(v) for k, v in extra.items()})
    return env


def _json_line(stdout: str) -> dict:
    for ln in stdout.splitlines():
        if ln.startswith("{"):
            return json.loads(ln)
    raise AssertionError(f"no JSON line in output: {stdout!r}")


def test_sigalrm_budget_edge_emits_json_on_hang():
    """A leg hung past the whole budget: the import-time SIGALRM guard
    prints the line and exits 0 instead of dying silently at rc=124."""
    out = subprocess.run(
        [sys.executable, BENCH],
        env=_env(BENCH_TIME_BUDGET="1", BENCH_ALARM_MARGIN="1"),
        capture_output=True, text=True, timeout=60)
    assert out.returncode == 0, out.stderr[-500:]
    line = _json_line(out.stdout)
    assert "error" in line
    assert "budget" in line["error"] or "signal" in line["error"]


def test_sigterm_first_strike_emits_json_on_hang():
    """The harness timeout's first strike (SIGTERM) during a hang still
    yields the one-line JSON — rc=124's silent death is unreachable while
    the interpreter can run a signal handler."""
    proc = subprocess.Popen(
        [sys.executable, BENCH],
        env=_env(BENCH_TIME_BUDGET="600"),
        stdout=subprocess.PIPE, stderr=subprocess.PIPE, text=True)
    time.sleep(2.0)                       # let the guards arm + hang start
    proc.send_signal(signal.SIGTERM)
    stdout, stderr = proc.communicate(timeout=30)
    assert proc.returncode == 0, stderr[-500:]
    line = _json_line(stdout)
    assert "terminated by signal" in line.get("error", "")


def test_tail_latency_keys_survive_forced_timeout():
    """ISSUE 9: the tail-latency headline keys (conc_p99_ms, shed_429s,
    hedged_wins) are seeded into the always-emitted line at import time,
    so a forced timeout mid-run still reports them (null, not absent)."""
    proc = subprocess.Popen(
        [sys.executable, BENCH],
        env=_env(BENCH_TIME_BUDGET="600"),
        stdout=subprocess.PIPE, stderr=subprocess.PIPE, text=True)
    time.sleep(2.0)
    proc.send_signal(signal.SIGTERM)
    stdout, stderr = proc.communicate(timeout=30)
    assert proc.returncode == 0, stderr[-500:]
    line = _json_line(stdout)
    for key in ("conc_p99_ms", "shed_429s", "hedged_wins",
                # quantized ANN tier (ISSUE 12): same seeded-null contract
                "knn_int8_qps", "knn_pq_qps", "pq_recall_at_10",
                "vector_stack_bytes_f32", "vector_stack_bytes_quantized",
                # chaos harness (ISSUE 14): same seeded-null contract
                "chaos_rounds", "chaos_parity_checks",
                "chaos_invariant_violations",
                # rebalance-under-load (ISSUE 15): same seeded-null
                # contract
                "rebalance_p99_ms", "rebalance_move_s",
                "recovery_throttle_bytes_per_sec", "decider_vetoes",
                # device telemetry flight recorder (ISSUE 16): same
                # seeded-null contract — the flight sidecar rides the
                # emergency line even when a kill lands mid-leg
                "xla_compile_ms_total", "hbm_peak_bytes",
                "lane_decision_counts", "flight",
                # log-analytics observability tier (ISSUE 17): same
                # seeded-null contract
                "sorted_mesh_qps", "sorted_fanout_qps",
                "subagg_mesh_qps", "monitoring_overview_p50_ms",
                # reverse search + script compiler (ISSUE 18): same
                # seeded-null contract
                "percolate_qps", "percolate_matrix_qps",
                "percolate_vs_loop", "script_score_qps",
                "script_vs_decline",
                # pod-scale serving (ISSUE 19): same seeded-null contract
                "pod_qps", "single_pool_qps", "pod_vs_single",
                "dcn_hops_per_query", "exec_lock_waits",
                # watcher alerting tier (ISSUE 20): same contract
                "watcher_evals_per_sec", "watcher_fire_p50_ms",
                "watcher_percolate_rides", "composite_page_qps"):
        assert key in line, f"[{key}] must survive a forced timeout"
        assert line[key] is None       # nothing measured before the kill


def test_guards_installed_before_first_leg():
    """Source-order tripwire: the bailout install happens at module scope
    (before any leg can run), not inside main_engine()."""
    src = open(BENCH).read()
    body = src.split("def _run_all_legs", 1)[0]
    assert "\n_install_bailout()" in body, \
        "_install_bailout() must run at import time, before the first leg"
    assert "SIGALRM" in src
    # per-leg budget enforcement by elapsed-time subtraction
    assert "_arm_leg_alarm" in src.split("def _run_all_legs", 1)[1]
