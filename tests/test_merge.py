"""Tensor-native merge: no re-tokenization (zero mapper calls), exact
search/agg/phrase parity across a merge, tombstone purge, ordinal remap,
and the size-tiered policy (VERDICT r3 task 4 done-bar).

ref index/merge/ + Lucene SegmentMerger semantics.
"""

import numpy as np
import pytest

from elasticsearch_tpu.index.engine import Engine
from elasticsearch_tpu.index.segment import merge_segments
from elasticsearch_tpu.mapping.mapper import MapperService
from elasticsearch_tpu.search.shard_searcher import ShardSearcher

MAPPING = {"_doc": {"properties": {
    "body": {"type": "text"},
    "tag": {"type": "keyword"},
    "price": {"type": "long"},
    "vec": {"type": "dense_vector", "dims": 4},
}}}

DOCS = [
    {"body": "quick brown fox", "tag": "zoo", "price": 10,
     "vec": [1, 0, 0, 0]},
    {"body": "quick quick dog", "tag": "apple", "price": 20,
     "vec": [0, 1, 0, 0]},
    {"body": "lazy fox sleeps", "tag": "mango", "price": 30,
     "vec": [0, 0, 1, 0]},
    {"body": "dog chases fox", "tag": "apple", "price": 40,
     "vec": [0, 0, 0, 1]},
    {"body": "nothing here", "tag": "berry", "price": 50,
     "vec": [1, 1, 0, 0]},
]


def _engine(tmp_path, refresh_every=2):
    mp = MapperService(mappings=MAPPING)
    eng = Engine(str(tmp_path), mp)
    for i, d in enumerate(DOCS):
        eng.index(str(i), d)
        if (i + 1) % refresh_every == 0:
            eng.refresh()
    eng.refresh()
    return eng, mp


def _search(eng, mp, body, **kw):
    s = ShardSearcher(0, eng.segments, mp)
    res = s.execute_query_phase(s.parse([body]), **kw)
    keys = [int(k) for k in res.doc_keys[0] if k >= 0]
    hits = s.execute_fetch_phase(keys, res.scores[0])
    return res, hits


class TestNativeMerge:
    def test_merge_makes_zero_mapper_calls(self, tmp_path):
        eng, mp = _engine(tmp_path)
        assert len(eng.segments) > 1
        calls = {"n": 0}
        orig = mp.document_mapper

        def spy(*a, **kw):
            calls["n"] += 1
            return orig(*a, **kw)

        mp.document_mapper = spy
        try:
            eng.force_merge(max_num_segments=1)
        finally:
            mp.document_mapper = orig
        assert calls["n"] == 0, "merge must not touch the mapper"
        assert len(eng.segments) == 1

    def test_search_parity_across_merge(self, tmp_path):
        eng, mp = _engine(tmp_path)
        bodies = [
            {"match": {"body": "quick fox"}},
            {"match_phrase": {"body": "quick brown fox"}},
            {"term": {"tag": "apple"}},
            {"range": {"price": {"gte": 20, "lte": 40}}},
        ]
        before = [_search(eng, mp, b) for b in bodies]
        eng.force_merge(max_num_segments=1)
        after = [_search(eng, mp, b) for b in bodies]
        for (bres, bhits), (ares, ahits) in zip(before, after):
            assert int(bres.total_hits[0]) == int(ares.total_hits[0])
            bmap = {h.doc_id: h.score for h in bhits}
            amap = {h.doc_id: h.score for h in ahits}
            assert set(bmap) == set(amap)
            for did in bmap:
                if not (np.isnan(bmap[did]) and np.isnan(amap[did])):
                    assert bmap[did] == pytest.approx(amap[did], rel=1e-5)

    def test_merge_purges_tombstones_and_keeps_versions(self, tmp_path):
        eng, mp = _engine(tmp_path)
        eng.index("1", {**DOCS[1], "price": 21})   # bump version
        eng.delete("2")
        eng.refresh()
        eng.force_merge(max_num_segments=1)
        seg = eng.segments[0]
        assert seg.n_docs == seg.live_count == 4          # doc 2 gone
        assert "2" not in seg.id_to_local
        local = seg.id_to_local["1"]
        assert seg.versions[local] == 2
        assert seg.stored[local]["price"] == 21
        res, hits = _search(eng, mp, {"match_all": {}})
        assert sorted(h.doc_id for h in hits) == ["0", "1", "3", "4"]

    def test_keyword_ordinals_remap_to_union_vocab(self, tmp_path):
        eng, mp = _engine(tmp_path)
        eng.force_merge(max_num_segments=1)
        kc = eng.segments[0].keywords["tag"]
        assert kc.values == sorted(kc.values)
        ords = np.asarray(kc.ords)
        for did, expect in [("0", "zoo"), ("1", "apple"), ("4", "berry")]:
            local = eng.segments[0].id_to_local[did]
            assert kc.values[int(ords[local])] == expect

    def test_vectors_and_positions_survive(self, tmp_path):
        eng, mp = _engine(tmp_path)
        eng.force_merge(max_num_segments=1)
        seg = eng.segments[0]
        local = seg.id_to_local["3"]
        assert np.allclose(np.asarray(seg.vectors["vec"].vecs)[local],
                           [0, 0, 0, 1])
        # phrase positions: "dog chases fox" must still phrase-match
        res, hits = _search(eng, mp, {"match_phrase": {"body": "chases fox"}})
        assert [h.doc_id for h in hits] == ["3"]

    def test_merge_empty_after_all_deleted(self, tmp_path):
        eng, mp = _engine(tmp_path)
        for i in range(len(DOCS)):
            eng.delete(str(i))
        eng.refresh()
        eng.force_merge(max_num_segments=1)
        assert eng.segments == []


class TestTieredPolicy:
    def test_small_tier_merges_do_not_touch_big_segment(self, tmp_path):
        mp = MapperService(mappings=MAPPING)
        eng = Engine(str(tmp_path), mp)
        # one big segment (64 docs = tier 2 at factor 8)
        for i in range(64):
            eng.index(f"big{i}", {"body": f"word{i} common"})
        eng.refresh()
        big = eng.segments[0]
        # 7 single-doc segments: still below the tier-0 fill of 8
        for i in range(7):
            eng.index(f"s{i}", {"body": "tiny common"})
            eng.refresh()
        assert big in eng.segments
        assert len(eng.segments) == 8
        # the 8th tier-0 segment fills the tier: ONE merge, big untouched
        eng.index("s7", {"body": "tiny common"})
        eng.refresh()
        assert big in eng.segments, "tiered merge must not rewrite big segs"
        assert len(eng.segments) == 2
        assert eng.doc_count() == 72

    def test_direct_merge_of_store_loaded_segments(self, tmp_path):
        # segments straight from a commit (host mirrors may be lazy)
        mp = MapperService(mappings=MAPPING)
        eng = Engine(str(tmp_path / "a"), mp)
        for i, d in enumerate(DOCS):
            eng.index(str(i), d)
            eng.refresh()
        eng.flush()
        eng.close()
        eng2 = Engine(str(tmp_path / "a"), mp)
        merged = merge_segments(eng2.segments, 99)
        assert merged.n_docs == len(DOCS)
        s = ShardSearcher(0, [merged], mp)
        res = s.execute_query_phase(s.parse([{"match": {"body": "fox"}}]))
        assert int(res.total_hits[0]) == 3
