"""REST over a REAL 3-node cluster: HttpServer fronting ClusterNode.

VERDICT r4 #1 — "front a ClusterHarness with HttpServer so REST requests
hit a real cluster". Requests enter over HTTP, coordinate via the
transport seam, and fan out to shards on three nodes.
"""

import json
import urllib.request

import pytest

from elasticsearch_tpu.cluster import TestCluster
from elasticsearch_tpu.rest import HttpServer
from elasticsearch_tpu.rest.cluster_gateway import register_cluster_routes


@pytest.fixture(scope="module")
def http(tmp_path_factory):
    cluster = TestCluster(3, str(tmp_path_factory.mktemp("chttp")))
    server = HttpServer(cluster.client(), port=0,
                        registrar=register_cluster_routes).start()
    yield cluster, f"http://127.0.0.1:{server.port}"
    server.stop()
    cluster.close()


def req(base, method, path, body=None, raw=False):
    data = None
    if body is not None:
        data = body if isinstance(body, bytes) \
            else json.dumps(body).encode()
    r = urllib.request.Request(base + path, data=data, method=method)
    try:
        resp = urllib.request.urlopen(r)
        code, payload = resp.status, resp.read()
    except urllib.error.HTTPError as e:
        code, payload = e.code, e.read()
    if raw:
        return code, payload.decode()
    return code, (json.loads(payload) if payload else {})


def test_cluster_over_http_end_to_end(http):
    cluster, base = http
    code, banner = req(base, "GET", "/")
    assert code == 200 and banner["tagline"] == "You Know, for Search"

    code, _ = req(base, "PUT", "/docs", {
        "settings": {"number_of_shards": 3, "number_of_replicas": 1},
        "mappings": {"_doc": {"properties": {
            "title": {"type": "string"},
            "n": {"type": "long"},
            "tag": {"type": "string", "index": "not_analyzed"}}}}})
    assert code == 200
    code, h = req(base, "GET", "/_cluster/health?wait_for_status=green")
    assert h["status"] == "green"
    assert h["number_of_nodes"] == 3
    assert h["active_shards"] == 6          # 3 primaries + 3 replicas

    # bulk over HTTP -> replicated writes across nodes
    lines = []
    for i in range(30):
        lines.append(json.dumps({"index": {"_index": "docs", "_id": str(i)}}))
        lines.append(json.dumps({"title": f"quick brown doc {i}",
                                 "n": i, "tag": ["a", "b", "c"][i % 3]}))
    code, out = req(base, "POST", "/_bulk?refresh=true",
                    ("\n".join(lines) + "\n").encode())
    assert code == 200 and out["errors"] is False

    # distributed search with aggs + sort over HTTP
    code, out = req(base, "POST", "/docs/_search", {
        "query": {"match": {"title": "quick"}},
        "sort": [{"n": "desc"}], "size": 5,
        "aggs": {"tags": {"terms": {"field": "tag"}},
                 "avg_n": {"avg": {"field": "n"}}}})
    assert code == 200
    assert out["hits"]["total"] == 30
    assert [h["sort"][0] for h in out["hits"]["hits"]] == [29, 28, 27, 26, 25]
    assert out["_shards"] == {"total": 3, "successful": 3, "failed": 0}
    assert out["aggregations"]["avg_n"]["value"] == pytest.approx(14.5)
    assert {b["key"]: b["doc_count"]
            for b in out["aggregations"]["tags"]["buckets"]} \
        == {"a": 10, "b": 10, "c": 10}

    # doc CRUD routed by id
    code, out = req(base, "GET", "/docs/_doc/7")
    assert code == 200 and out["_source"]["n"] == 7
    code, out = req(base, "DELETE", "/docs/_doc/7?refresh=true")
    assert code == 200
    code, out = req(base, "GET", "/docs/_doc/7")
    assert code == 404

    # count
    code, out = req(base, "GET", "/docs/_count")
    assert out["count"] == 29

    # scroll over HTTP
    code, out = req(base, "POST", "/docs/_search?scroll=1m",
                    {"query": {"match_all": {}}, "size": 10})
    sid = out["_scroll_id"]
    seen = [h["_id"] for h in out["hits"]["hits"]]
    while True:
        code, out = req(base, "POST", "/_search/scroll",
                        {"scroll_id": sid, "scroll": "1m"})
        if not out["hits"]["hits"]:
            break
        seen.extend(h["_id"] for h in out["hits"]["hits"])
    assert len(seen) == 29 and len(set(seen)) == 29
    code, out = req(base, "DELETE", "/_search/scroll", {"scroll_id": sid})
    assert out["found"] is True

    # msearch
    body = "\n".join([
        json.dumps({"index": "docs"}),
        json.dumps({"query": {"term": {"tag": "a"}}, "size": 0}),
        json.dumps({"index": "docs"}),
        json.dumps({"size": 0,
                    "aggs": {"m": {"max": {"field": "n"}}}})]) + "\n"
    code, out = req(base, "POST", "/_msearch", body.encode())
    assert out["responses"][0]["hits"]["total"] == 10
    assert out["responses"][1]["aggregations"]["m"]["value"] == 29

    # mapping round-trip over the master
    code, _ = req(base, "PUT", "/docs/_mapping/_doc",
                  {"properties": {"extra": {"type": "long"}}})
    code, out = req(base, "GET", "/docs/_mapping")
    assert out["docs"]["mappings"]["_doc"]["properties"]["extra"][
        "type"] == "long"

    # cat endpoints
    code, txt = req(base, "GET", "/_cat/shards", raw=True)
    assert code == 200 and "docs" in txt and " p " in txt
    code, txt = req(base, "GET", "/_cat/nodes", raw=True)
    assert "*" in txt and len(txt.strip().split("\n")) == 3


def test_http_search_survives_node_loss(http):
    cluster, base = http
    code, _ = req(base, "PUT", "/ha", {
        "settings": {"number_of_shards": 2, "number_of_replicas": 1}})
    req(base, "GET", "/_cluster/health?wait_for_status=green")
    for i in range(10):
        req(base, "PUT", f"/ha/_doc/{i}", {"v": i})
    req(base, "POST", "/ha/_refresh")
    # kill a node that is NOT the HTTP coordinator; replicas cover
    coordinator = cluster.client().node_id
    victim = next(nid for nid in cluster.nodes if nid != coordinator)
    cluster.kill_node(victim)
    cluster.detect_once()
    code, out = req(base, "GET", "/_cluster/health?wait_for_status=yellow")
    code, out = req(base, "POST", "/ha/_search",
                    {"query": {"match_all": {}}, "size": 10})
    assert code == 200
    assert out["hits"]["total"] == 10       # replicas served the dead node's


def test_nodes_stats_fan_out(http):
    """Every live node answers the nodes template over the transport
    (ref TransportNodesStatsAction fan-out)."""
    cluster, base = http
    code, out = req(base, "GET", "/_nodes/stats")
    assert code == 200
    live = [n for n, cn in cluster.nodes.items() if not cn.closed]
    assert set(out["nodes"]) == set(live)
    for stats in out["nodes"].values():
        assert stats["os"]["mem"]["total_in_bytes"] > 0
        assert stats["fs"]["total"]["total_in_bytes"] > 0
        assert "indices" in stats


def test_indices_stats_broadcast(http):
    """Shard stats aggregate across every copy-holding node (the broadcast
    template; ref TransportBroadcastOperationAction)."""
    cluster, base = http
    code, out = req(base, "GET", "/ha/_stats")
    assert code == 200
    st = out["indices"]["ha"]["total"]
    assert st["docs"]["count"] >= 10            # primaries + replicas
    assert out["_shards"]["failed"] == 0
    assert st["shard_copies"] >= 1
    code, out = req(base, "GET", "/_stats")
    assert code == 200 and out["_all"]["total"]["docs"]["count"] >= 10


def test_cluster_metrics_fan_out_with_failures(http):
    """/_cluster/_metrics merges per-node expositions into one document
    (same family, one sample per node) and reports a live node whose
    handler errors as a failure entry instead of dropping the scrape."""
    cluster, base = http
    code, text = req(base, "GET", "/_cluster/_metrics", raw=True)
    assert code == 200
    assert text.endswith("# EOF\n")
    live = {n for n, cn in cluster.nodes.items() if not cn.closed}
    sample_nodes = set()
    type_lines = []
    for ln in text.splitlines():
        if ln.startswith("# TYPE "):
            type_lines.append(ln.split()[2])
        elif ln and not ln.startswith("#") and 'node="' in ln:
            sample_nodes.add(ln.split('node="')[1].split('"')[0])
    assert sample_nodes == live               # one exposition, every node
    assert len(type_lines) == len(set(type_lines))   # families merge
    assert "es_tasks_running" in type_lines

    # a LIVE node whose handler errors surfaces as a failure comment
    coordinator = cluster.client().node_id
    victim = next(n for n in sorted(live) if n != coordinator)
    from elasticsearch_tpu.cluster.node import A_NODE_METRICS

    def broken(from_id, req_):
        raise RuntimeError("scrape handler down")
    cluster.nodes[victim].transport.register_handler(A_NODE_METRICS, broken)
    code, text = req(base, "GET", "/_cluster/_metrics", raw=True)
    assert code == 200
    assert f"# node-failure node={victim}" in text

    # the single-node exposition also serves from the gateway
    code, text = req(base, "GET", "/_metrics", raw=True)
    assert code == 200 and "# TYPE es_tasks_running gauge" in text
