"""Serving-QoS subsystem (ISSUE 9): coalesced cross-request batching
parity, admission control + load shedding (429, never 5xx), hedged
replica reads, transport traffic classes, and the observability plumbing.

Contract pins:
  * follower-served coalesced batches are BITWISE-identical to solo
    execution across the query-shape matrix (the `_search_batched`
    replica-axis executor is the seam);
  * overload sheds as 429 + Retry-After — at the QoS admission gate
    (class budgets, EWMA pressure with a fake clock) and at the bounded
    search pool (EsRejectedExecutionException at the REST boundary);
  * a slow replica's query hedges onto another copy, completes under the
    injected delay, and the loser's cancellation is observed;
  * saturating the bulk transport class leaves a reg-class round-trip
    under deadline (per-class connection budgets, NettyTransport's five
    connection types);
  * batcher anomalies (stranded followers, wait timeouts, swallowed run
    errors) are counted, and the qos/hedge/transport-class registries
    ride `/_metrics` + the sampler ring with correct exposition types.
"""

import json
import threading
import time

import pytest

from elasticsearch_tpu.common.settings import Settings
from elasticsearch_tpu.node import NodeService
from elasticsearch_tpu.serving.batcher import LEAD, SearchBatcher
from elasticsearch_tpu.serving.qos import (Ewma, QosController,
                                           QosShedException, hedge_snapshot)

WORDS = ["quick", "brown", "fox", "jumps", "lazy", "dog", "sleeps",
         "swift", "river", "stone"]

MAPPING = {"_doc": {"properties": {
    "body": {"type": "string"},
    "tag": {"type": "string", "index": "not_analyzed"},
    "n": {"type": "long"},
    "price": {"type": "double"}}}}

# the query-shape matrix (tests/test_mesh.py's 19 shapes): every shape the
# coalesced general lane may batch must serve followers bitwise-identically
QUERY_SHAPES = [
    {"match_all": {}},
    {"bool": {"should": [{"match": {"body": "fox"}},
                         {"match": {"body": "dog"}}]}},
    {"bool": {"should": [{"match": {"body": "quick"}}],
              "filter": [{"range": {"n": {"gte": 2, "lt": 60}}}]}},
    {"term": {"tag": "t1"}},
    {"terms": {"tag": ["t0", "t2"]}},
    {"term": {"n": 4}},
    {"term": {"price": 6.5}},
    {"range": {"n": {"gt": 30}}},
    {"range": {"price": {"gte": 2.0, "lt": 50.0}}},
    {"range": {"tag": {"gte": "t0", "lte": "t1"}}},
    {"exists": {"field": "price"}},
    {"exists": {"field": "body"}},
    {"ids": {"values": ["1", "5", "8", "77"]}},
    {"ids": {"values": ["zzz-absent"]}},
    {"constant_score": {"filter": {"term": {"tag": "t1"}}, "boost": 2.5}},
    {"dis_max": {"queries": [{"match": {"body": "fox"}},
                             {"match": {"body": "dog"}}],
                 "tie_breaker": 0.4}},
    {"bool": {"must": [{"match": {"body": "fox"}}],
              "must_not": [{"term": {"tag": "t2"}}],
              "should": [{"match": {"body": "brown"}}]}},
    {"bool": {"should": [{"match": {"body": {"query": "fox brown",
                                             "operator": "and"}}}]}},
    {"bool": {"should": [{"match": {"body": "quick"}},
                         {"match": {"body": "river"}}],
              "minimum_should_match": 2}},
]


@pytest.fixture(scope="module")
def node(tmp_path_factory):
    n = NodeService(str(tmp_path_factory.mktemp("qos")))
    n.create_index("q", settings={"number_of_shards": 4},
                   mappings=MAPPING)
    di = 0
    for _ in range(3):
        for _ in range(16):
            doc = {"body": f"{WORDS[di % 10]} {WORDS[(di * 3 + 1) % 10]} "
                           f"{WORDS[(di * 7 + 2) % 10]}",
                   "tag": f"t{di % 3}", "n": di}
            if di % 2 == 0:
                doc["price"] = di / 2.0
            n.index_doc("q", str(di), doc)
            di += 1
        n.refresh("q")
    yield n
    n.close()


def _strip_took(resp: dict) -> dict:
    out = json.loads(json.dumps(resp))
    out.pop("took", None)
    return out


def _search(n, body):
    return n.search("q", json.loads(json.dumps(body)))


# ---------------------------------------------------------------------------
# 1. coalesced cross-request batching: bitwise parity with solo execution
# ---------------------------------------------------------------------------


class TestCoalescedBatchParity:
    @pytest.mark.parametrize("q", QUERY_SHAPES,
                             ids=[json.dumps(q)[:48] for q in QUERY_SHAPES])
    def test_batched_rows_bitwise_identical_to_solo(self, node, q):
        """Every matrix shape served through the coalesced lane's executor
        (Q=2 batch) must match its solo execution byte for byte."""
        body = {"size": 10, "query": q, "_source": False}
        solo = _strip_took(_search(node, body))
        outs = node._search_batched([("q", json.loads(json.dumps(body))),
                                     ("q", json.loads(json.dumps(body)))])
        assert len(outs) == 2
        for row in outs:
            assert _strip_took(row) == solo, q

    def test_followers_ride_one_batch_and_match_solo(self, node):
        """End-to-end through the lane: a held leader accumulates
        followers; drain serves them as ONE Q>1 batch whose responses are
        bitwise-identical to their solo responses."""
        bodies = [{"size": 10, "query": {"match": {"body": w}},
                   "_source": True, "from": 0}
                  for w in ("quick", "river", "stone", "lazy")]
        # packed-ineligible twist (so the packed lane can't intercept):
        # _source: True bodies with a bool wrapper share one plan shape
        bodies = [{"size": 10, "_source": True,
                   "query": {"bool": {"should": [{"match": {"body": w}}],
                                      "filter": [{"range": {
                                          "n": {"gte": 0}}}]}}}
                  for w in ("quick", "river", "stone", "lazy")]
        solos = [_strip_took(_search(node, b)) for b in bodies]
        keys = [node._msearch_batch_key("q", b) for b in bodies]
        assert all(k is not None and k == keys[0] for k in keys), \
            "same-shape bodies must share one coalescing group"

        got = node._batcher.join_batched(keys[0], bodies[0])
        assert got is LEAD          # this thread now holds leadership
        results: dict[int, dict] = {}
        threads = [threading.Thread(
            target=lambda i=i: results.__setitem__(
                i, _search(node, bodies[i])))
            for i in range(1, 4)]
        before = node._batcher.stats()
        for t in threads:
            t.start()
        deadline = time.time() + 5
        while time.time() < deadline:
            with node._batcher._lock:
                qd = len(node._batcher._queues.get(("gen", *keys[0]), []))
            if qd == 3:
                break
            time.sleep(0.01)
        assert qd == 3, "followers did not queue behind the leader"
        node._batcher.drain_batched(keys[0], "q")
        for t in threads:
            t.join()
        after = node._batcher.stats()
        assert after["batches"] == before["batches"] + 1, \
            "three followers must share ONE device batch"
        assert after["batched_requests"] == before["batched_requests"] + 3
        for i in range(1, 4):
            assert _strip_took(results[i]) == solos[i], bodies[i]

    def test_solo_path_unchanged_when_lane_disabled(self, node):
        body = {"size": 10, "query": {"term": {"tag": "t1"}}}
        on = _strip_took(_search(node, body))
        node.settings._map["node.search.qos.enable"] = False
        try:
            off = _strip_took(_search(node, body))
        finally:
            node.settings._map.pop("node.search.qos.enable", None)
        assert on == off


# ---------------------------------------------------------------------------
# 2. admission control + load shedding
# ---------------------------------------------------------------------------


class TestAdmissionControl:
    def _controller(self, overrides=None, clock=None):
        s = Settings({"node.search.qos.max_inflight": 10,
                      **(overrides or {})})
        return QosController(s, clock=clock or (lambda: 0.0))

    def test_ewma_latency_pressure_sheds_search(self):
        """Fake-clock EWMA: sustained device latency above the shed
        ceiling drives pressure to 1.0 and search admission sheds with a
        Retry-After hint; control-plane classes stay admitted."""
        t = [0.0]
        qos = self._controller({"node.search.qos.shed_latency_ms": 1000},
                               clock=lambda: t[0])
        for _ in range(8):
            t[0] += 1.0
            qos.record_latency(2000.0)     # way past the 1000 ms ceiling
        assert qos.latency_frac() == 1.0
        assert qos.pressure() >= 1.0
        with pytest.raises(QosShedException) as ei:
            qos.admit("search")
        assert ei.value.retry_after_s >= 1.0
        assert qos.class_stats()["search"]["shed_total"] == 1
        # state/ping are never shed — a cluster must keep its heartbeats
        with qos.admit("state"):
            pass
        with qos.admit("ping"):
            pass

    def test_stale_latency_signal_decays_and_unsheds(self):
        """Shed livelock tripwire (ISSUE 12 satellite): one compile-heavy
        request spikes the EWMA past the ceiling; because shed requests
        never execute, no new sample can arrive — the stale signal must
        DECAY with idle time so probe traffic gets admitted again."""
        t = [0.0]
        qos = self._controller({"node.search.qos.shed_latency_ms": 1000},
                               clock=lambda: t[0])
        qos.record_latency(30_000.0)       # one 30s compile+train query
        assert qos.latency_frac() == 1.0
        with pytest.raises(QosShedException):
            qos.admit("search")
        t[0] += 120.0                      # two minutes idle: 4 half-lives
        assert qos.latency_frac() < 0.1
        with qos.admit("search"):          # admitted: signal re-measures
            pass
        # <=0 half-life restores the undecayed (pre-fix) signal
        qos2 = self._controller(
            {"node.search.qos.shed_latency_ms": 1000,
             "node.search.qos.latency_halflife_s": 0},
            clock=lambda: t[0])
        qos2.record_latency(30_000.0)
        t[0] += 600.0
        assert qos2.latency_frac() == 1.0

    def test_degrade_band_shrinks_batch_window_before_shedding(self):
        t = [0.0]
        qos = self._controller({"node.search.qos.shed_latency_ms": 1000,
                                "node.search.qos.degrade_threshold": 0.5,
                                "node.search.qos.shed_threshold": 0.95},
                               clock=lambda: t[0])
        for _ in range(8):
            qos.record_latency(700.0)      # ~0.7 of the ceiling: degrade
        with qos.admit("search"):          # admitted, but degraded
            pass
        assert qos.degraded
        assert qos.batch_window(32) < 32
        assert qos.follower_wait_s() <= 30.0
        # healthy latencies recover the full window
        qos2 = self._controller({"node.search.qos.shed_latency_ms": 1000})
        qos2.record_latency(5.0)
        with qos2.admit("search"):
            pass
        assert not qos2.degraded
        assert qos2.batch_window(32) == 32

    def test_class_budget_isolation(self):
        """Saturating the bulk class budget sheds BULK, not search."""
        qos = self._controller({"node.search.qos.bulk.share": 0.2})
        holds = [qos.admit("bulk"), qos.admit("bulk")]   # 2 = 10 * 0.2
        with pytest.raises(QosShedException):
            qos.admit("bulk")
        with qos.admit("search"):          # search budget untouched
            pass
        for h in holds:
            h.__exit__(None, None, None)
        with qos.admit("bulk"):            # slots released -> admitted
            pass

    def test_http_shed_is_429_with_retry_after_never_5xx(self, tmp_path):
        """The REST boundary: a shed search is 429 + Retry-After (the
        client-visible backpressure signal), and flipping the budget back
        restores 200 — no 5xx anywhere."""
        import urllib.error
        import urllib.request
        from elasticsearch_tpu.rest import HttpServer
        n = NodeService(str(tmp_path / "shed"))
        n.create_index("s", mappings={"_doc": {"properties": {
            "body": {"type": "string"}}}})
        n.index_doc("s", "1", {"body": "hello world"})
        n.refresh("s")
        srv = HttpServer(n, port=0).start()
        base = f"http://127.0.0.1:{srv.port}"
        body = json.dumps({"query": {"match": {"body": "hello"}}}).encode()

        def post():
            req = urllib.request.Request(base + "/s/_search", data=body,
                                         method="POST")
            with urllib.request.urlopen(req) as r:
                return r.status, json.loads(r.read()), dict(r.headers)
        try:
            status, _, _ = post()
            assert status == 200
            n.settings._map["node.search.qos.search.share"] = 0   # 0 slots
            with pytest.raises(urllib.error.HTTPError) as ei:
                post()
            assert ei.value.code == 429
            assert int(ei.value.headers["Retry-After"]) >= 1
            payload = json.loads(ei.value.read())
            assert payload["status"] == 429
            assert "QosShed" in payload["error"]
            assert n.qos.class_stats()["search"]["shed_total"] >= 1
            n.settings._map.pop("node.search.qos.search.share")
            status, _, _ = post()          # recovery: back to 200
            assert status == 200
        finally:
            srv.stop()
            n.close()

    def test_search_pool_rejection_maps_to_429_with_retry_after(
            self, tmp_path):
        """ISSUE 9 satellite: bounded-pool overflow
        (EsRejectedExecutionException) surfaces at the REST boundary as
        EXACTLY 429 + Retry-After, not a raise/5xx."""
        import urllib.error
        import urllib.request
        from elasticsearch_tpu.rest import HttpServer
        # QoS admission off: the point is the POOL's rejection path (the
        # admission gate would otherwise shed first on queue pressure)
        n = NodeService(str(tmp_path / "rej"),
                        settings=Settings({
                            "node.search.qos.enable": False,
                            "threadpool.search.size": 1,
                            "threadpool.search.queue_size": 1}))
        n.create_index("s", mappings={"_doc": {"properties": {
            "body": {"type": "string"}}}})
        n.index_doc("s", "1", {"body": "hello"})
        n.refresh("s")
        srv = HttpServer(n, port=0).start()
        base = f"http://127.0.0.1:{srv.port}"
        release = threading.Event()
        started = threading.Event()

        def plug():
            started.set()
            release.wait(10)
        try:
            pool = n.thread_pool.pools["search"]
            assert pool.size == 1 and pool.queue_size == 1
            pool.execute(plug)             # occupies the single worker
            assert started.wait(5)
            pool.execute(lambda: None)     # fills the queue of 1
            body = json.dumps({"query": {"match_all": {}}}).encode()
            req = urllib.request.Request(base + "/s/_search", data=body,
                                         method="POST")
            with pytest.raises(urllib.error.HTTPError) as ei:
                urllib.request.urlopen(req)
            assert ei.value.code == 429
            assert "Retry-After" in ei.value.headers
            assert json.loads(ei.value.read())["status"] == 429
            assert pool.rejected >= 1
        finally:
            release.set()
            srv.stop()
            n.close()


# ---------------------------------------------------------------------------
# 3. hedged replica reads
# ---------------------------------------------------------------------------


@pytest.fixture()
def cluster2(tmp_path):
    from elasticsearch_tpu.cluster import TestCluster
    c = TestCluster(2, str(tmp_path))
    yield c
    c.close()


A_QUERY = "indices:data/read/search[phase/query]"


class TestHedgedReads:
    def _prime(self, cluster):
        client = cluster.client()
        client.create_index("h", {"number_of_shards": 1,
                                  "number_of_replicas": 1})
        cluster.ensure_green()
        for i in range(20):
            client.index_doc("h", str(i),
                             {"body": f"{WORDS[i % 10]} common"})
        client.refresh("h")
        # warm BOTH copies' latency EWMAs (round-robin alternates them)
        for _ in range(6):
            client.search("h", {"query": {"match": {"body": "common"}}})
        return client

    def test_hedge_beats_injected_slow_replica(self, cluster2):
        client = self._prime(cluster2)
        client.hedge_settings["cluster.search.hedge.min_ms"] = 30
        state = client.cluster.current()
        copies = state.started_copies("h", 0)
        assert len(copies) == 2
        rr = client._read_rr.get(("h", 0), 0)
        slow = copies[rr % len(copies)]["node"]   # the NEXT serving copy
        before = dict(client.hedge_stats)
        base = hedge_snapshot()
        cluster2.network.add_delay(slow, A_QUERY, 1.5)
        try:
            t0 = time.perf_counter()
            out = client.search("h", {"query": {"match": {"body":
                                                          "common"}}})
            took = time.perf_counter() - t0
        finally:
            cluster2.network.clear_delay(slow, A_QUERY)
        assert out["hits"]["total"] == 20
        assert took < 1.2, \
            f"hedged query must complete under the healthy copy's " \
            f"latency, took {took:.2f}s against a 1.5s-slow copy"
        assert client.hedge_stats["fired"] == before["fired"] + 1
        assert client.hedge_stats["win_backup"] == \
            before["win_backup"] + 1
        # the loser (the delayed copy) eventually answers and its
        # cancellation is OBSERVED, not silently leaked
        deadline = time.time() + 5
        while time.time() < deadline \
                and client.hedge_stats["canceled"] <= before["canceled"]:
            time.sleep(0.05)
        assert client.hedge_stats["canceled"] == before["canceled"] + 1
        snap = hedge_snapshot()
        assert snap["fired"] >= base["fired"] + 1
        assert snap["win_backup"] >= base["win_backup"] + 1

    def test_hedge_disabled_setting_means_no_hedge(self, cluster2):
        client = self._prime(cluster2)
        client.hedge_settings["cluster.search.hedge.enable"] = False
        client.hedge_settings["cluster.search.hedge.min_ms"] = 30
        state = client.cluster.current()
        copies = state.started_copies("h", 0)
        rr = client._read_rr.get(("h", 0), 0)
        slow = copies[rr % len(copies)]["node"]
        before = dict(client.hedge_stats)
        cluster2.network.add_delay(slow, A_QUERY, 0.4)
        try:
            t0 = time.perf_counter()
            out = client.search("h", {"query": {"match": {"body":
                                                          "common"}}})
            took = time.perf_counter() - t0
        finally:
            cluster2.network.clear_delay(slow, A_QUERY)
        assert out["hits"]["total"] == 20
        assert took >= 0.4                  # ate the full delay: no hedge
        assert client.hedge_stats == before

    def test_hedge_span_parents_under_query_span(self, cluster2):
        client = self._prime(cluster2)
        client.hedge_settings["cluster.search.hedge.min_ms"] = 30
        state = client.cluster.current()
        copies = state.started_copies("h", 0)
        rr = client._read_rr.get(("h", 0), 0)
        slow = copies[rr % len(copies)]["node"]
        cluster2.network.add_delay(slow, A_QUERY, 1.0)
        try:
            with client.tracer.request("POST /h/_search", force=True):
                client.search("h", {"query": {"match": {"body":
                                                        "common"}}})
        finally:
            cluster2.network.clear_delay(slow, A_QUERY)
        from elasticsearch_tpu.common.tracing import span_tree
        traces = client.tracer.list()
        assert traces
        tree = span_tree(
            client.tracer.get(traces[0]["trace_id"]))["tree"]

        def find(node, name):
            if node["name"] == name:
                return node
            for ch in node.get("children", []):
                got = find(ch, name)
                if got is not None:
                    return got
            return None
        query = find(tree, "query")
        assert query is not None, "coordinator query span missing"
        hedge = find(query, "hedge")
        assert hedge is not None, "hedge span must sit under query"
        assert hedge["attributes"]["backup"] != slow


# ---------------------------------------------------------------------------
# 4. transport traffic classes
# ---------------------------------------------------------------------------


class TestTrafficClasses:
    def test_class_of_action_mapping(self):
        from elasticsearch_tpu.cluster.transport import class_of_action
        assert class_of_action(
            "internal:index/shard/recovery/chunk") == "recovery"
        assert class_of_action("indices:data/write/op[p]") == "bulk"
        assert class_of_action("indices:data/write/op[r]") == "bulk"
        assert class_of_action(
            "internal:discovery/zen/fd/ping") == "ping"
        assert class_of_action("internal:cluster/shard/started") == "state"
        assert class_of_action("indices:admin/create") == "state"
        assert class_of_action(
            "indices:data/read/search[phase/query]") == "reg"
        assert class_of_action("indices:data/read/get") == "reg"

    def test_bulk_saturation_leaves_reg_class_under_deadline(self):
        """NettyTransport.java:180-184's point: the bulk class's 3
        connections saturate and queue, while a reg-class (query)
        round-trip on the SAME node pair completes immediately."""
        from elasticsearch_tpu.cluster import (LocalTransport,
                                               TransportService)
        net = LocalTransport()
        a = TransportService("a", net)
        b = TransportService("b", net)
        b.register_handler("indices:data/write/op[p]",
                           lambda frm, req: "ok")
        b.register_handler("indices:data/read/search[phase/query]",
                           lambda frm, req: {"hits": 1})
        net.add_delay("b", "indices:data/write", 0.4)
        done = []
        threads = [threading.Thread(
            target=lambda: done.append(
                a.send("b", "indices:data/write/op[p]", {})))
            for _ in range(6)]
        for t in threads:
            t.start()
        time.sleep(0.1)                 # 3 in flight, 3 queued
        st = net.class_stats()
        assert st["bulk"]["queue_depth"] >= 1, \
            "bulk sends past the connection budget must queue"
        t0 = time.perf_counter()
        out = a.send("b", "indices:data/read/search[phase/query]", {})
        took = time.perf_counter() - t0
        assert out == {"hits": 1}
        assert took < 0.3, \
            f"reg-class round-trip must not queue behind bulk ({took:.2f}s)"
        for t in threads:
            t.join()
        assert len(done) == 6           # saturation delayed, never dropped
        st = net.class_stats()
        assert st["bulk"]["max_queue_depth"] >= 2
        assert st["bulk"]["sent_total"] >= 6
        assert st["reg"]["sent_total"] >= 1
        assert st["bulk"]["queue_depth"] == 0   # drained clean

    def test_nested_same_pair_sends_reenter_held_connection(self):
        """state class has ONE connection; a handler that sends another
        state-class message to the same pair must re-enter, not deadlock."""
        from elasticsearch_tpu.cluster import (LocalTransport,
                                               TransportService)
        net = LocalTransport()
        a = TransportService("a", net)

        def outer(frm, req):
            if req.get("depth", 0) < 2:
                return a.send("a", "internal:cluster/nested",
                              {"depth": req.get("depth", 0) + 1})
            return "bottom"
        a.register_handler("internal:cluster/nested", outer)
        assert a.send("a", "internal:cluster/nested", {}) == "bottom"


# ---------------------------------------------------------------------------
# 5. batcher anomaly accounting (ISSUE 9 satellite)
# ---------------------------------------------------------------------------


class _StubQos:
    def __init__(self, wait_s=0.05):
        self._wait = wait_s

    def batch_window(self, base):
        return base

    def follower_wait_s(self):
        return self._wait


class _StubNode:
    def __init__(self, wait_s=0.05):
        self.qos = _StubQos(wait_s)
        self.metrics = None

    def _search_batched(self, metas):
        return [{"served": body} for _, body in metas]

    def _packed_error(self):
        pass


class TestBatcherAccounting:
    def test_follower_wait_timeout_counted_and_falls_back(self):
        node = _StubNode(wait_s=0.05)
        b = SearchBatcher(node)
        key = ("k",)
        assert b.join_batched(key, {"q": 0}) is LEAD
        got = []
        th = threading.Thread(
            target=lambda: got.append(b.join_batched(key, {"q": 1})))
        th.start()
        th.join(5)              # leader never drains: follower times out
        assert got == [None], "timed-out follower must fall to general"
        assert b.stats()["wait_timeouts_total"] == 1
        b.drain_batched(key, "i")   # abandoned entry must not be served
        assert b.stats()["batches"] == 0

    def test_stranded_followers_counted_and_released(self):
        node = _StubNode(wait_s=5.0)
        b = SearchBatcher(node)
        key = ("k",)
        assert b.join_batched(key, {"q": 0}) is LEAD
        got = []
        th = threading.Thread(
            target=lambda: got.append(b.join_batched(key, {"q": 1})))
        th.start()
        deadline = time.time() + 5
        while time.time() < deadline:
            with b._lock:
                if b._queues.get(("gen", "k")):
                    break
            time.sleep(0.01)
        # leader exits WITHOUT draining (the leftover path): the follower
        # must be released to the general path and counted as stranded
        b._release(("gen", "k"))
        th.join(5)
        assert got == [None]
        assert b.stats()["stranded_total"] == 1

    def test_run_error_recorded_not_discarded(self):
        node = _StubNode(wait_s=5.0)

        def boom(metas):
            raise RuntimeError("device fell over")
        node._search_batched = boom
        b = SearchBatcher(node)
        key = ("k",)
        assert b.join_batched(key, {"q": 0}) is LEAD
        got = []
        th = threading.Thread(
            target=lambda: got.append(b.join_batched(key, {"q": 1})))
        th.start()
        deadline = time.time() + 5
        while time.time() < deadline:
            with b._lock:
                if b._queues.get(("gen", "k")):
                    break
            time.sleep(0.01)
        b.drain_batched(key, "i")
        th.join(5)
        assert got == [None], "a failing batch degrades to general"
        st = b.stats()
        assert st["run_errors_total"] == 1
        assert "device fell over" in st["last_error"]


# ---------------------------------------------------------------------------
# 6. observability plumbing: /_metrics exposition, sampler ring
# ---------------------------------------------------------------------------


class TestQosObservability:
    def test_qos_families_exposed_with_correct_types(self, node):
        from elasticsearch_tpu.common.metrics import render_openmetrics
        from tests.test_metrics_exposition import parse_openmetrics
        _search(node, {"query": {"match": {"body": "quick"}}})
        families = parse_openmetrics(
            render_openmetrics(node.metric_sections()))
        for fam, mtype in (("es_qos_shed_total", "counter"),
                           ("es_qos_admitted_total", "counter"),
                           ("es_qos_inflight", "gauge"),
                           ("es_qos_node_pressure", "gauge"),
                           ("es_qos_node_ewma_latency_ms", "gauge"),
                           ("es_qos_node_degraded_total", "counter"),
                           ("es_search_hedged_total", "counter"),
                           ("es_search_batcher_stranded_total", "counter"),
                           ("es_search_batcher_wait_timeouts_total",
                            "counter"),
                           ("es_search_batcher_run_errors_total",
                            "counter")):
            assert fam in families, fam
            assert families[fam]["type"] == mtype, fam
        classes = {lb["class"] for lb, _ in
                   families["es_qos_shed_total"]["samples"]}
        assert classes == {"search", "bulk", "recovery", "state", "ping"}
        outcomes = {lb["outcome"] for lb, _ in
                    families["es_search_hedged_total"]["samples"]}
        assert {"fired", "win_backup", "win_primary",
                "canceled"} <= outcomes

    def test_transport_class_families_exposed(self, cluster2):
        from elasticsearch_tpu.common.metrics import render_openmetrics
        from tests.test_metrics_exposition import parse_openmetrics
        n = cluster2.client()
        families = parse_openmetrics(
            render_openmetrics(n.metric_sections(), node=n.node_id))
        assert families["es_transport_class_queue_depth"]["type"] == "gauge"
        assert families["es_transport_class_sent_total"]["type"] \
            == "counter"
        classes = {lb["class"] for lb, _ in
                   families["es_transport_class_queue_depth"]["samples"]}
        # "dcn" is the sixth class (ISSUE 19): cross-host latency traffic
        assert classes == {"recovery", "bulk", "reg", "state", "ping",
                           "dcn"}

    def test_sampler_ring_gains_qos_gauges(self, node):
        snap = node._sampler_snapshot()
        for key in ("qos_pressure", "qos_queue_depth", "qos_shed_rate_1m",
                    "qos_shed_total", "qos_degraded", "hedge_rate_1m",
                    "hedged_fired_total", "batcher_stranded_total",
                    "batcher_wait_timeouts_total"):
            assert key in snap, key

    def test_ewma_deadline_tracks_tail(self):
        e = Ewma()
        for _ in range(50):
            e.observe(10.0)
        assert 9.0 < e.value < 11.0
        assert e.deadline_ms() < 30.0       # tight latencies, tight deadline
        e2 = Ewma()
        for v in (10.0, 200.0, 10.0, 300.0, 15.0, 250.0):
            e2.observe(v)
        assert e2.deadline_ms() > e2.value  # jitter widens the deadline
