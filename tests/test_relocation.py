"""Shard rebalancing (RELOCATING copy-then-switch) + streaming delta peer
recovery (VERDICT r4 #7/#9).

Ref: cluster/routing/allocation/allocator/BalancedShardsAllocator.java,
ShardRouting RELOCATING state machine, indices/recovery/
RecoverySourceHandler.java:149-195 (chunk streaming + checksum delta).
"""

import pytest

from elasticsearch_tpu.cluster import TestCluster
from elasticsearch_tpu.cluster.state import RELOCATING, STARTED


def _settle(cluster, rounds=60):
    import time
    for _ in range(rounds):
        cluster.detect_once()
        st = cluster.client().cluster.current()
        busy = any(
            c["state"] != STARTED
            for shards in st.routing.values()
            for copies in shards for c in copies)
        if not busy:
            return st
        time.sleep(0.05)
    return cluster.client().cluster.current()


class TestRebalancing:
    def test_joining_node_receives_shards(self, tmp_path):
        cluster = TestCluster(2, str(tmp_path))
        try:
            client = cluster.client()
            client.create_index("docs", {"number_of_shards": 4,
                                         "number_of_replicas": 0})
            cluster.ensure_green()
            for i in range(40):
                client.index_doc("docs", str(i), {"n": i})
            client.refresh("docs")
            new_node = cluster.add_node()
            st = _settle(cluster)
            by_node: dict = {}
            for copies in st.routing["docs"]:
                for c in copies:
                    by_node[c["node"]] = by_node.get(c["node"], 0) + 1
            # 4 shards over 3 nodes: nobody holds more than 2, and the
            # NEW node actually received at least one
            assert max(by_node.values()) <= 2
            assert by_node.get(new_node.node_id, 0) >= 1
            # every doc still reachable after the moves
            out = client.search("docs", {"query": {"match_all": {}},
                                         "size": 40})
            assert out["hits"]["total"] == 40
        finally:
            cluster.close()

    def test_relocation_preserves_data_and_writes(self, tmp_path):
        cluster = TestCluster(1, str(tmp_path))
        try:
            client = cluster.client()
            client.create_index("docs", {"number_of_shards": 2,
                                         "number_of_replicas": 0})
            cluster.ensure_green()
            for i in range(30):
                client.index_doc("docs", str(i), {"n": i})
            client.refresh("docs")
            cluster.add_node()
            st = _settle(cluster)
            nodes_used = {c["node"] for copies in st.routing["docs"]
                          for c in copies}
            assert len(nodes_used) == 2      # one shard moved over
            # writes after the move land on the new owner
            client.index_doc("docs", "99", {"n": 99})
            client.refresh("docs")
            assert client.get_doc("docs", "99")["found"]
            out = client.search("docs", {"query": {"match_all": {}},
                                         "size": 50})
            assert out["hits"]["total"] == 31
        finally:
            cluster.close()

    def test_relocating_source_keeps_serving(self, tmp_path):
        from elasticsearch_tpu.cluster.state import (ClusterState,
                                                     new_index_routing,
                                                     rebalance)
        st = ClusterState.empty()
        st.nodes["a"] = {"id": "a"}
        st.nodes["b"] = {"id": "b"}
        st.data["routing"]["i"] = new_index_routing(2, 0)
        for copies in st.routing["i"]:
            copies[0]["node"] = "a"
            copies[0]["state"] = STARTED
        assert rebalance(st)
        copies0 = [c for shards in st.routing.values()
                   for copies in shards for c in copies
                   if c["state"] == RELOCATING]
        assert len(copies0) == 1
        # the relocating source still counts as a started (read-serving)
        # copy of its shard
        sid = next(sid for sid, copies in enumerate(st.routing["i"])
                   if any(c["state"] == RELOCATING for c in copies))
        assert any(c["state"] == RELOCATING
                   for c in st.started_copies("i", sid))


class TestStreamingRecovery:
    def test_recovery_is_chunked_and_delta(self, tmp_path):
        cluster = TestCluster(2, str(tmp_path))
        try:
            client = cluster.client()
            client.create_index("big", {"number_of_shards": 1,
                                        "number_of_replicas": 1})
            cluster.ensure_green()
            # enough docs that the store files exceed one recovery chunk
            payload = "tok " * 200
            for i in range(800):
                client.index_doc("big", str(i), {"body": payload + str(i)})
            client.flush("big")

            # force a re-recovery of the replica through the chunk protocol
            from elasticsearch_tpu.cluster.node import ClusterNode
            ClusterNode.RECOVERY_CHUNK = 1 << 14      # 16 KiB for the test
            try:
                st = client.cluster.current()
                replica_node = next(
                    c["node"] for c in st.shard_copies("big", 0)
                    if not c["primary"])
                cluster.network.max_message_bytes = 0
                master = cluster.master_node()
                master._on_shard_failed(master.node_id, {
                    "index": "big", "shard": 0, "node": replica_node})
                cluster.ensure_green()
                # every recovery frame stayed within chunk bounds (payload
                # b64-encoded + framing; 3x is generous)
                assert cluster.network.max_message_bytes < (1 << 14) * 3
            finally:
                ClusterNode.RECOVERY_CHUNK = 1 << 19
            # the replica serves the data it recovered
            st = client.cluster.current()
            holders = [n._shards[("big", 0)] for n in cluster.nodes.values()
                       if ("big", 0) in n._shards]
            assert len(holders) == 2
            for h in holders:
                assert h.engine.get("500").found
        finally:
            cluster.close()

    def test_delta_reuse_skips_unchanged_files(self, tmp_path):
        cluster = TestCluster(2, str(tmp_path))
        try:
            client = cluster.client()
            client.create_index("d", {"number_of_shards": 1,
                                      "number_of_replicas": 1})
            cluster.ensure_green()
            for i in range(300):
                client.index_doc("d", str(i), {"n": i})
            client.flush("d")
            cluster.ensure_green()
            st = client.cluster.current()
            replica_node = next(c["node"] for c in st.shard_copies("d", 0)
                                if not c["primary"])
            master = cluster.master_node()
            # first re-recovery: files arrive
            master._on_shard_failed(master.node_id, {
                "index": "d", "shard": 0, "node": replica_node})
            cluster.ensure_green()
            bytes_first = cluster.network.bytes_sent
            # second re-recovery with NO new data: the checksum delta
            # reuses every segment file — only manifest + translog move
            st = client.cluster.current()
            replica_node = next(c["node"] for c in st.shard_copies("d", 0)
                                if not c["primary"])
            before = cluster.network.bytes_sent
            master._on_shard_failed(master.node_id, {
                "index": "d", "shard": 0, "node": replica_node})
            cluster.ensure_green()
            delta_bytes = cluster.network.bytes_sent - before
            first_bytes = bytes_first
            assert delta_bytes < first_bytes / 2, \
                (delta_bytes, first_bytes)
        finally:
            cluster.close()
