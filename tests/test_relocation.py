"""Shard rebalancing (RELOCATING copy-then-switch) + streaming delta peer
recovery (VERDICT r4 #7/#9).

Ref: cluster/routing/allocation/allocator/BalancedShardsAllocator.java,
ShardRouting RELOCATING state machine, indices/recovery/
RecoverySourceHandler.java:149-195 (chunk streaming + checksum delta).
"""

import time

import pytest

from elasticsearch_tpu.cluster import TestCluster
from elasticsearch_tpu.cluster.state import (RELOCATING, STARTED,
                                             UNASSIGNED)


def _settle(cluster, rounds=60):
    import time
    for _ in range(rounds):
        cluster.detect_once()
        st = cluster.client().cluster.current()
        busy = any(
            c["state"] != STARTED
            for shards in st.routing.values()
            for copies in shards for c in copies)
        if not busy:
            return st
        time.sleep(0.05)
    return cluster.client().cluster.current()


class TestRebalancing:
    def test_joining_node_receives_shards(self, tmp_path):
        cluster = TestCluster(2, str(tmp_path))
        try:
            client = cluster.client()
            client.create_index("docs", {"number_of_shards": 4,
                                         "number_of_replicas": 0})
            cluster.ensure_green()
            for i in range(40):
                client.index_doc("docs", str(i), {"n": i})
            client.refresh("docs")
            new_node = cluster.add_node()
            st = _settle(cluster)
            by_node: dict = {}
            for copies in st.routing["docs"]:
                for c in copies:
                    by_node[c["node"]] = by_node.get(c["node"], 0) + 1
            # 4 shards over 3 nodes: nobody holds more than 2, and the
            # NEW node actually received at least one
            assert max(by_node.values()) <= 2
            assert by_node.get(new_node.node_id, 0) >= 1
            # every doc still reachable after the moves
            out = client.search("docs", {"query": {"match_all": {}},
                                         "size": 40})
            assert out["hits"]["total"] == 40
        finally:
            cluster.close()

    def test_relocation_preserves_data_and_writes(self, tmp_path):
        cluster = TestCluster(1, str(tmp_path))
        try:
            client = cluster.client()
            client.create_index("docs", {"number_of_shards": 2,
                                         "number_of_replicas": 0})
            cluster.ensure_green()
            for i in range(30):
                client.index_doc("docs", str(i), {"n": i})
            client.refresh("docs")
            cluster.add_node()
            st = _settle(cluster)
            nodes_used = {c["node"] for copies in st.routing["docs"]
                          for c in copies}
            assert len(nodes_used) == 2      # one shard moved over
            # writes after the move land on the new owner
            client.index_doc("docs", "99", {"n": 99})
            client.refresh("docs")
            assert client.get_doc("docs", "99")["found"]
            out = client.search("docs", {"query": {"match_all": {}},
                                         "size": 50})
            assert out["hits"]["total"] == 31
        finally:
            cluster.close()

    def test_relocating_source_keeps_serving(self, tmp_path):
        from elasticsearch_tpu.cluster.state import (ClusterState,
                                                     new_index_routing,
                                                     rebalance)
        st = ClusterState.empty()
        st.nodes["a"] = {"id": "a"}
        st.nodes["b"] = {"id": "b"}
        st.data["routing"]["i"] = new_index_routing(2, 0)
        for copies in st.routing["i"]:
            copies[0]["node"] = "a"
            copies[0]["state"] = STARTED
        assert rebalance(st)
        copies0 = [c for shards in st.routing.values()
                   for copies in shards for c in copies
                   if c["state"] == RELOCATING]
        assert len(copies0) == 1
        # the relocating source still counts as a started (read-serving)
        # copy of its shard
        sid = next(sid for sid, copies in enumerate(st.routing["i"])
                   if any(c["state"] == RELOCATING for c in copies))
        assert any(c["state"] == RELOCATING
                   for c in st.started_copies("i", sid))


class TestStreamingRecovery:
    def test_recovery_is_chunked_and_delta(self, tmp_path):
        cluster = TestCluster(2, str(tmp_path))
        try:
            client = cluster.client()
            client.create_index("big", {"number_of_shards": 1,
                                        "number_of_replicas": 1})
            cluster.ensure_green()
            # enough docs that the store files exceed one recovery chunk
            payload = "tok " * 200
            for i in range(800):
                client.index_doc("big", str(i), {"body": payload + str(i)})
            client.flush("big")

            # force a re-recovery of the replica through the chunk protocol
            from elasticsearch_tpu.cluster.node import ClusterNode
            ClusterNode.RECOVERY_CHUNK = 1 << 14      # 16 KiB for the test
            try:
                st = client.cluster.current()
                replica_node = next(
                    c["node"] for c in st.shard_copies("big", 0)
                    if not c["primary"])
                cluster.network.max_message_bytes = 0
                master = cluster.master_node()
                master._on_shard_failed(master.node_id, {
                    "index": "big", "shard": 0, "node": replica_node})
                cluster.ensure_green()
                # every recovery frame stayed within chunk bounds (payload
                # b64-encoded + framing; 3x is generous)
                assert cluster.network.max_message_bytes < (1 << 14) * 3
            finally:
                ClusterNode.RECOVERY_CHUNK = 1 << 19
            # the replica serves the data it recovered
            st = client.cluster.current()
            holders = [n._shards[("big", 0)] for n in cluster.nodes.values()
                       if ("big", 0) in n._shards]
            assert len(holders) == 2
            for h in holders:
                assert h.engine.get("500").found
        finally:
            cluster.close()

    def test_delta_reuse_skips_unchanged_files(self, tmp_path):
        cluster = TestCluster(2, str(tmp_path))
        try:
            client = cluster.client()
            client.create_index("d", {"number_of_shards": 1,
                                      "number_of_replicas": 1})
            cluster.ensure_green()
            for i in range(300):
                client.index_doc("d", str(i), {"n": i})
            client.flush("d")
            cluster.ensure_green()
            st = client.cluster.current()
            replica_node = next(c["node"] for c in st.shard_copies("d", 0)
                                if not c["primary"])
            master = cluster.master_node()
            # first re-recovery: files arrive
            master._on_shard_failed(master.node_id, {
                "index": "d", "shard": 0, "node": replica_node})
            cluster.ensure_green()
            bytes_first = cluster.network.bytes_sent
            # second re-recovery with NO new data: the checksum delta
            # reuses every segment file — only manifest + translog move
            st = client.cluster.current()
            replica_node = next(c["node"] for c in st.shard_copies("d", 0)
                                if not c["primary"])
            before = cluster.network.bytes_sent
            master._on_shard_failed(master.node_id, {
                "index": "d", "shard": 0, "node": replica_node})
            cluster.ensure_green()
            delta_bytes = cluster.network.bytes_sent - before
            first_bytes = bytes_first
            assert delta_bytes < first_bytes / 2, \
                (delta_bytes, first_bytes)
        finally:
            cluster.close()


def _fail_replica(cluster, index: str, wipe: bool = True,
                  timeout: float = 60.0) -> str:
    """Report the replica of [index][0] failed, wait for the resulting
    re-recovery to reach a terminal stage, and return the node id — the
    canonical way these tests force a fresh peer recovery. With `wipe`
    the replica's local files go first, so the recovery STREAMS every
    byte instead of reusing it all through the checksum delta. The wait
    matters: the fail task publishes asynchronously and the pull streams
    on a background thread, so without it the caller races a recovery
    that hasn't started yet."""
    import shutil
    st = cluster.client().cluster.current()
    replica_node = next(c["node"] for c in st.shard_copies(index, 0)
                        if not c["primary"])
    target = cluster.nodes[replica_node]
    if wipe:
        with target._shards_lock:
            holder = target._shards.pop((index, 0), None)
        if holder is not None and holder.engine is not None:
            holder.drop_searcher()
            holder.engine.close()
        shutil.rmtree(target._shard_path(index, 0), ignore_errors=True)
    mark = time.monotonic()
    master = cluster.master_node()
    master._on_shard_failed(master.node_id, {
        "index": index, "shard": 0, "node": replica_node})
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        with target._recoveries_lock:
            rec = target.recoveries.get((index, 0))
            fresh = (rec is not None and rec["start_s"] >= mark
                     and rec["stage"] in ("done", "failed", "cancelled"))
        if fresh:
            return replica_node
        time.sleep(0.02)
    raise AssertionError(f"re-recovery of [{index}][0] never finished")


class TestRecoveryThrottle:
    """indices.recovery.max_bytes_per_sec through the actual chunk
    stream (ISSUE 15): a token bucket on the receiving side paces every
    recovery the node runs."""

    def test_throttle_paces_the_stream_and_counts_waits(self, tmp_path):
        from elasticsearch_tpu.cluster.recovery import (parse_bytes,
                                                        snapshot)
        cluster = TestCluster(2, str(tmp_path))
        try:
            client = cluster.client()
            client.create_index("t", {"number_of_shards": 1,
                                      "number_of_replicas": 1})
            cluster.ensure_green()
            payload = "tok " * 200
            for i in range(500):
                client.index_doc("t", str(i), {"body": payload + str(i)})
            client.flush("t")
            cluster.ensure_green()
            client.update_cluster_settings(
                {"indices.recovery.max_bytes_per_sec": "100kb"})
            r0 = dict(snapshot())
            t0 = time.monotonic()
            _fail_replica(cluster, "t")
            cluster.ensure_green(timeout=60.0)
            dt = time.monotonic() - t0
            r1 = dict(snapshot())
            moved = r1["bytes_total"] - r0["bytes_total"]
            assert moved > parse_bytes("100kb") / 2, moved
            assert r1["throttle_waits_total"] > r0["throttle_waits_total"]
            # compliance: measured rate stays within the limit plus the
            # burst allowance (bucket capacity = rate/2)
            assert moved / dt <= parse_bytes("100kb") * 3, (moved, dt)
            # and it actually slowed down: an unthrottled local recovery
            # of ~500 KiB completes in well under a second
            assert dt > 1.0, dt
        finally:
            cluster.close()

    def test_unlimited_rate_never_waits(self, tmp_path):
        from elasticsearch_tpu.cluster.recovery import snapshot
        cluster = TestCluster(2, str(tmp_path))
        try:
            client = cluster.client()
            client.create_index("u", {"number_of_shards": 1,
                                      "number_of_replicas": 1})
            cluster.ensure_green()
            for i in range(200):
                client.index_doc("u", str(i), {"n": i})
            client.flush("u")
            cluster.ensure_green()
            client.update_cluster_settings(
                {"indices.recovery.max_bytes_per_sec": 0})
            r0 = dict(snapshot())
            _fail_replica(cluster, "u")
            cluster.ensure_green()
            r1 = dict(snapshot())
            assert r1["bytes_total"] > r0["bytes_total"]
            assert r1["throttle_waits_total"] == r0["throttle_waits_total"]
        finally:
            cluster.close()

    def test_parse_bytes(self):
        from elasticsearch_tpu.cluster.recovery import parse_bytes
        assert parse_bytes("40mb") == 40 * (1 << 20)
        assert parse_bytes("100kb") == 100 * 1024
        assert parse_bytes("1gb") == 1 << 30
        assert parse_bytes("512b") == 512.0
        assert parse_bytes(123456) == 123456.0
        assert parse_bytes(0) == 0.0          # 0 / negative = unlimited
        assert parse_bytes("-1") == 0.0
        assert parse_bytes("garbage", default=7.0) == 7.0


class TestChunkRetry:
    def test_transient_chunk_fault_is_resent_with_backoff(self, tmp_path):
        """A dropped chunk send retries the SAME bounded read instead of
        failing the whole recovery — only the final exhaustion aborts."""
        from elasticsearch_tpu.cluster.node import A_RECOVERY_CHUNK
        from elasticsearch_tpu.cluster.recovery import snapshot
        from elasticsearch_tpu.cluster.transport import \
            ConnectTransportException
        cluster = TestCluster(2, str(tmp_path))
        try:
            client = cluster.client()
            client.create_index("r", {"number_of_shards": 1,
                                      "number_of_replicas": 1})
            cluster.ensure_green()
            for i in range(300):
                client.index_doc("r", str(i), {"n": i})
            client.flush("r")
            cluster.ensure_green()
            st = client.cluster.current()
            replica_node = next(c["node"]
                                for c in st.shard_copies("r", 0)
                                if not c["primary"])
            target = cluster.nodes[replica_node]
            import shutil
            with target._shards_lock:
                holder = target._shards.pop(("r", 0), None)
            if holder is not None and holder.engine is not None:
                holder.drop_searcher()
                holder.engine.close()
            shutil.rmtree(target._shard_path("r", 0), ignore_errors=True)
            real_send = target.transport.send
            fails = {"left": 2}

            def flaky(dest, action, payload, **kw):
                if action == A_RECOVERY_CHUNK and fails["left"] > 0:
                    fails["left"] -= 1
                    raise ConnectTransportException("injected chunk fault")
                return real_send(dest, action, payload, **kw)

            target.transport.send = flaky
            r0 = dict(snapshot())
            try:
                master = cluster.master_node()
                master._on_shard_failed(master.node_id, {
                    "index": "r", "shard": 0, "node": replica_node})
                # the pull streams on a background thread: wait for ITS
                # completion, not for a (possibly stale) green health
                deadline = time.monotonic() + 30.0
                while time.monotonic() < deadline:
                    if dict(snapshot())["completed_total"] \
                            > r0["completed_total"]:
                        break
                    time.sleep(0.02)
                cluster.ensure_green(timeout=30.0)
            finally:
                target.transport.send = real_send
            r1 = dict(snapshot())
            assert r1["retries_total"] - r0["retries_total"] >= 2
            assert r1["completed_total"] > r0["completed_total"]
            assert fails["left"] == 0
            rows = [r for r in client.cat_recovery()
                    if r["index"] == "r" and r["stage"] == "done"]
            assert rows and rows[-1]["retries"] >= 2
        finally:
            cluster.close()


class TestRecoveryCancellation:
    def test_cancel_mid_stream_cleans_up(self, tmp_path):
        """Unassigning a copy mid-recovery (here: index deletion) aborts
        the pull between chunks, GCs the partial files and never reports
        the copy started."""
        from elasticsearch_tpu.cluster.node import (A_RECOVERY_CHUNK,
                                                    ClusterNode)
        from elasticsearch_tpu.cluster.recovery import snapshot
        cluster = TestCluster(2, str(tmp_path))
        old_chunk = ClusterNode.RECOVERY_CHUNK
        try:
            client = cluster.client()
            client.create_index("c", {"number_of_shards": 1,
                                      "number_of_replicas": 1})
            cluster.ensure_green()
            payload = "tok " * 200
            for i in range(400):
                client.index_doc("c", str(i), {"body": payload + str(i)})
            client.flush("c")
            cluster.ensure_green()
            st = client.cluster.current()
            primary_node = st.primary_of("c", 0)["node"]
            replica_node = next(c["node"]
                                for c in st.shard_copies("c", 0)
                                if not c["primary"])
            target = cluster.nodes[replica_node]
            import shutil
            with target._shards_lock:
                holder = target._shards.pop(("c", 0), None)
            if holder is not None and holder.engine is not None:
                holder.drop_searcher()
                holder.engine.close()
            shutil.rmtree(target._shard_path("c", 0), ignore_errors=True)
            # many tiny chunks, each paying injected latency: the stream
            # stays in flight long enough to cancel deterministically
            ClusterNode.RECOVERY_CHUNK = 1 << 13
            cluster.network.add_delay(primary_node, A_RECOVERY_CHUNK, 0.05)
            r0 = dict(snapshot())
            master = cluster.master_node()
            master._on_shard_failed(master.node_id, {
                "index": "c", "shard": 0, "node": replica_node})
            deadline = time.monotonic() + 10.0
            while time.monotonic() < deadline:
                rec = target.recoveries.get(("c", 0))
                if rec is not None and rec["bytes_recovered"] > 0 \
                        and rec["stage"] not in ("done", "failed"):
                    break
                time.sleep(0.01)
            else:
                raise AssertionError("recovery never got in flight")
            client.delete_index("c")
            deadline = time.monotonic() + 10.0
            while time.monotonic() < deadline:
                if dict(snapshot())["cancelled_total"] \
                        > r0["cancelled_total"]:
                    break
                time.sleep(0.02)
            r1 = dict(snapshot())
            assert r1["cancelled_total"] > r0["cancelled_total"]
            assert r1["completed_total"] == r0["completed_total"]
            # partial files GC'd, nothing reported started
            import os
            assert not os.path.exists(target._shard_path("c", 0))
            assert ("c", 0) not in target._shards
        finally:
            ClusterNode.RECOVERY_CHUNK = old_chunk
            cluster.network.heal()
            cluster.close()


class TestRelocationRaces:
    """finish_relocation / cancel_relocations_for interleavings
    (ISSUE 15 satellite): a relocation target dying the same tick as the
    source's finish ack must not leave a zombie `relocating_to`."""

    def _relocating_state(self):
        from elasticsearch_tpu.cluster.state import (ClusterState,
                                                     new_index_routing)
        st = ClusterState.empty()
        st.nodes["a"] = {"id": "a"}
        st.nodes["b"] = {"id": "b"}
        st.data["routing"]["i"] = new_index_routing(1, 0)
        src = st.routing["i"][0][0]
        src.update({"node": "a", "state": RELOCATING,
                    "relocating_to": "b"})
        st.routing["i"][0].append({
            "node": "b", "primary": False, "state": "INITIALIZING",
            "relocation": True, "recover_from": "a",
            "primary_target": True})
        return st

    def test_cancel_then_finish_leaves_no_zombie(self, tmp_path):
        from elasticsearch_tpu.cluster.state import (cancel_relocations_for,
                                                     finish_relocation)
        st = self._relocating_state()
        cancel_relocations_for(st, "b")        # target node died
        assert not finish_relocation(st, "i", 0, "b")   # stale finish ack
        copies = st.routing["i"][0]
        assert len(copies) == 1
        assert copies[0]["state"] == STARTED
        assert "relocating_to" not in copies[0]

    def test_finish_sweeps_stale_pointer_when_source_reverted(self):
        """The zombie shape itself: the source was reverted to STARTED
        (concurrent cancel) but still carries the pointer when the finish
        ack lands — finish must clear it, or every later finish/cancel
        sweep double-counts the copy."""
        from elasticsearch_tpu.cluster.state import finish_relocation
        st = self._relocating_state()
        src = st.routing["i"][0][0]
        src["state"] = STARTED                 # reverted, pointer stale
        assert finish_relocation(st, "i", 0, "b")
        copies = st.routing["i"][0]
        assert all("relocating_to" not in c for c in copies)
        tgt = next(c for c in copies if c["node"] == "b")
        assert tgt["state"] == STARTED and tgt["primary"]
        assert not tgt.get("relocation")

    def test_source_failure_mid_relocation_reverts_cleanly(self, tmp_path):
        """_on_shard_failed on a RELOCATING source: the pointer pops, the
        orphaned target drops, the primary reverts to STARTED (it holds
        the only data) — and the drain then retries to completion."""
        from elasticsearch_tpu.cluster.node import (A_RECOVERY_CHUNK,
                                                    ClusterNode)
        cluster = TestCluster(2, str(tmp_path))
        old_chunk = ClusterNode.RECOVERY_CHUNK
        try:
            client = cluster.client()
            client.create_index("z", {"number_of_shards": 1,
                                      "number_of_replicas": 0})
            cluster.ensure_green()
            payload = "tok " * 200
            for i in range(300):
                client.index_doc("z", str(i), {"body": payload + str(i)})
            client.flush("z")
            st = client.cluster.current()
            src_node = st.primary_of("z", 0)["node"]
            other = next(n for n in cluster.nodes if n != src_node)
            ClusterNode.RECOVERY_CHUNK = 1 << 13
            # chunk requests flow TO the source node: delay THAT link so
            # the relocation stays observable mid-stream
            cluster.network.add_delay(src_node, A_RECOVERY_CHUNK, 0.05)
            master = cluster.master_node()
            client.update_cluster_settings(
                {"cluster.routing.allocation.exclude._id": src_node})
            deadline = time.monotonic() + 10.0
            while time.monotonic() < deadline:
                cur = master.cluster.current()
                if any(c["state"] == RELOCATING
                       for c in cur.shard_copies("z", 0)):
                    break
                time.sleep(0.01)
            else:
                raise AssertionError("relocation never started")
            # the SOURCE is reported failed while RELOCATING
            master._on_shard_failed(master.node_id, {
                "index": "z", "shard": 0, "node": src_node})

            def clean(cur):
                copies = cur.shard_copies("z", 0)
                return (all("relocating_to" not in c for c in copies)
                        and not any(c.get("relocation") for c in copies))

            deadline = time.monotonic() + 10.0
            while time.monotonic() < deadline:
                cur = master.cluster.current()
                revert = next((c for c in cur.shard_copies("z", 0)
                               if c["node"] == src_node), None)
                if revert is not None and revert["state"] in (
                        STARTED, RELOCATING):
                    break
                time.sleep(0.01)
            cur = master.cluster.current()
            assert not any(
                c["state"] == UNASSIGNED and "relocating_to" in c
                for c in cur.shard_copies("z", 0))
            # heal the stream: the exclude filter retries and the drain
            # completes with no zombie markers anywhere
            cluster.network.heal()
            deadline = time.monotonic() + 20.0
            while time.monotonic() < deadline:
                cluster.detect_once()
                cur = master.cluster.current()
                copies = cur.shard_copies("z", 0)
                if (clean(cur) and len(copies) == 1
                        and copies[0]["node"] == other
                        and copies[0]["state"] == STARTED):
                    break
                time.sleep(0.05)
            copies = master.cluster.current().shard_copies("z", 0)
            assert copies[0]["node"] == other, copies
            assert copies[0]["state"] == STARTED
            assert clean(master.cluster.current())
            out = client.search("z", {"query": {"match_all": {}},
                                      "size": 1})
            assert out["hits"]["total"] == 300
        finally:
            ClusterNode.RECOVERY_CHUNK = old_chunk
            cluster.network.heal()
            cluster.close()


class TestCatRecoveryAndObservability:
    def test_cat_recovery_rows_and_metrics(self, tmp_path):
        from elasticsearch_tpu.cluster.recovery import snapshot
        cluster = TestCluster(2, str(tmp_path))
        try:
            client = cluster.client()
            client.create_index("cr", {"number_of_shards": 1,
                                       "number_of_replicas": 1})
            cluster.ensure_green()
            for i in range(200):
                client.index_doc("cr", str(i), {"n": i})
            client.flush("cr")
            cluster.ensure_green()
            replica_node = _fail_replica(cluster, "cr")
            cluster.ensure_green()
            rows = [r for r in client.cat_recovery() if r["index"] == "cr"]
            done = [r for r in rows if r["stage"] == "done"]
            assert done, rows
            row = done[-1]
            for key in ("index", "shard", "source", "target", "stage",
                        "files_total", "files_reused", "bytes_total",
                        "bytes_recovered", "throttle_waits", "retries",
                        "start_time_ms", "elapsed_ms"):
                assert key in row, key
            assert row["target"] == replica_node
            assert row["bytes_recovered"] > 0
            assert row["elapsed_ms"] >= 0
            # the node-level metric section behind
            # es_recovery_bytes_total / es_recovery_throttle_waits_total
            sections = cluster.master_node().metric_sections()
            label, counters = sections["recovery"]
            assert label is None
            assert counters["bytes_total"] == snapshot()["bytes_total"]
            assert "throttle_waits_total" in counters
            # the recovery trace roots on the TARGET with per-chunk spans
            target = cluster.nodes[replica_node]
            tid = next(t["trace_id"] for t in target.tracer.list()
                       if t["root"] == "recovery")
            trace = target.tracer.get(tid)
            names = {s["name"] for s in trace["spans"]}
            assert "recovery_chunk" in names
            chunk = next(s for s in trace["spans"]
                         if s["name"] == "recovery_chunk")
            assert chunk["attributes"]["bytes"] > 0
        finally:
            cluster.close()


class TestAllocationIdFence:
    """Every (re)assignment stamps a fresh allocation id; started/failed
    reports only act on the era they came from (ref AllocationId). The
    chaos kill/restart roster caught the unfenced version: a restarted
    process's PRE-KILL pull completing late marked the copy's NEW (and
    actually failed) assignment STARTED — a zombie serving nothing."""

    def test_assigned_copies_carry_unique_aids(self, tmp_path):
        cluster = TestCluster(2, str(tmp_path))
        try:
            client = cluster.client()
            client.create_index("z", {"number_of_shards": 2,
                                      "number_of_replicas": 1})
            cluster.ensure_green()
            st = cluster.master_node().cluster.current()
            aids = [c.get("aid")
                    for copies in st.routing["z"] for c in copies]
            assert all(a is not None for a in aids), aids
            assert len(aids) == len(set(aids)), aids
        finally:
            cluster.close()

    def test_stale_era_reports_are_ignored(self, tmp_path):
        cluster = TestCluster(2, str(tmp_path))
        try:
            client = cluster.client()
            client.create_index("z", {"number_of_shards": 1,
                                      "number_of_replicas": 1})
            cluster.ensure_green()
            master = cluster.master_node()
            st = master.cluster.current()
            replica = next(c for c in st.shard_copies("z", 0)
                           if not c["primary"])
            cur_aid = replica["aid"]
            # a started AND a failed report from a previous era: neither
            # may touch the current, healthy assignment
            master._on_shard_started(master.node_id, {
                "index": "z", "shard": 0, "node": replica["node"],
                "aid": cur_aid - 1})
            master._on_shard_failed(master.node_id, {
                "index": "z", "shard": 0, "node": replica["node"],
                "aid": cur_aid - 1})
            # both handlers queue wait=False tasks: a sync no-op task
            # behind them is the drain barrier (the state thread is FIFO)
            master.cluster.submit_task("barrier", lambda cur: None)
            after = next(c for c in master.cluster.current()
                         .shard_copies("z", 0) if not c["primary"])
            assert after["state"] == STARTED
            assert after["node"] == replica["node"]
            assert after["aid"] == cur_aid
        finally:
            cluster.close()

    def test_reassignment_gets_a_new_aid(self, tmp_path):
        cluster = TestCluster(2, str(tmp_path))
        try:
            client = cluster.client()
            client.create_index("z", {"number_of_shards": 1,
                                      "number_of_replicas": 1})
            cluster.ensure_green()
            master = cluster.master_node()
            st = master.cluster.current()
            replica = next(c for c in st.shard_copies("z", 0)
                           if not c["primary"])
            old_aid = replica["aid"]
            # fail the CURRENT era (correct aid): unassign + re-allocate
            master._on_shard_failed(master.node_id, {
                "index": "z", "shard": 0, "node": replica["node"],
                "aid": old_aid})
            deadline = time.monotonic() + 30.0
            while time.monotonic() < deadline:
                cur = next(c for c in master.cluster.current()
                           .shard_copies("z", 0) if not c["primary"])
                if cur["state"] == STARTED and cur["aid"] != old_aid:
                    break
                time.sleep(0.02)
            cur = next(c for c in master.cluster.current()
                       .shard_copies("z", 0) if not c["primary"])
            assert cur["state"] == STARTED
            assert cur["aid"] > old_aid
        finally:
            cluster.close()
